#!/usr/bin/env python
"""Compile-and-run every kernel family on the REAL TPU chip (world size 1).

Interpret mode (the test suite's backend) accepts some programs real
Mosaic rejects — this script is the hardware truth check the driver's
single-chip ``entry()`` compile-check samples only one path of. Run on any
TPU host:

    python scripts/check_on_chip.py

Exit code 0 = every family compiled AND executed. The multi-rank variants
of the same kernels differ only in loop counts and remote device ids
(validated functionally on the CPU mesh; real multi-chip needs a pod).
"""

import functools
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


class FloorError(RuntimeError):
    """A perf floor was violated — a hardware/toolchain regression, not
    window noise (floors carry ~2x slack; obs/gate.py ON_CHIP_FLOORS)."""


def _retry_windows(fn, attempts: int = 3):
    """Floors use bench.py's fail-loud differential chains; a contended
    window raises BenchError — retry it, never a FloorError (a violated
    floor from a CLEAN measurement must not get lucky on retry)."""
    import bench

    last = None
    for attempt in range(attempts):
        try:
            return fn()
        except bench.BenchError as e:
            last = e
            if attempt < attempts - 1:
                import time

                time.sleep(3)
    raise last


def floor_gemm_tflops() -> float:
    """Sustained TFLOP/s of the pinned headline GEMM ((2048,5120)@
    (5120,5120) bf16, tiles (1024,1024,512)) must clear
    ON_CHIP_FLOORS['gemm_tflops_min'] (trajectory: 165.6-178.3)."""
    import bench
    from triton_distributed_tpu.obs.gate import ON_CHIP_FLOORS
    from triton_distributed_tpu.ops.gemm import pallas_matmul

    M, K, lengths = 2048, 5120, (16, 64, 128)
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.05, jnp.bfloat16)
    b = bench._orthogonal_b(K, jnp.bfloat16)
    fn = jax.jit(functools.partial(
        bench._chain, lambda x, w: pallas_matmul(
            x, w, tile_m=1024, tile_n=1024, tile_k=512)),
        static_argnums=2)
    flops = 2.0 * M * K * K

    def measure():
        times = bench._timed_interleaved([fn], a, b, lengths, trials=3)
        per = bench._per_iter_seconds(times[0], lengths, flops,
                                      strict=True)
        return flops / per / 1e12

    tflops = _retry_windows(measure)
    floor = ON_CHIP_FLOORS["gemm_tflops_min"]
    print(f"       GEMM sustained {tflops:.1f} TFLOP/s "
          f"(floor {floor:g})")
    if tflops < floor:
        raise FloorError(f"GEMM {tflops:.1f} TFLOP/s below floor "
                         f"{floor:g} — half clocks / broken MXU path?")
    return tflops


def floor_flash32k_ms() -> float:
    """Per-call ms of the S=32k causal flash prefill (B=1, 8q/1kv,
    d=128, 1024x1024 tiles) must stay under
    ON_CHIP_FLOORS['flash32k_prefill_ms_max'] (measured ~12 ms)."""
    import bench
    from triton_distributed_tpu.obs.gate import ON_CHIP_FLOORS
    from triton_distributed_tpu.ops.flash_attention import flash_attention

    S = 32768
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((1, S, 8, 128)) * 0.3,
                    jnp.bfloat16)
    kv = (jnp.asarray(rng.standard_normal((1, S, 1, 128)) * 0.3,
                      jnp.bfloat16),
          jnp.asarray(rng.standard_normal((1, S, 1, 128)) * 0.3,
                      jnp.bfloat16))

    # Dependent chain (out feeds the next q — same layout), differenced
    # over two lengths so relay dispatch cost cancels (bench.py method).
    @functools.partial(jax.jit, static_argnums=2)
    def chain(q0, kv_, n):
        def body(i, x):
            return flash_attention(x, kv_[0], kv_[1], causal=True)

        out = jax.lax.fori_loop(0, n, body, q0)
        return jnp.sum(out.astype(jnp.float32))

    lengths = (2, 6, 10)
    flops = 2.0 * S * S * 8 * 128          # causal ~half of 4*S^2*h*d

    def measure():
        times = bench._timed_interleaved([chain], q, kv, lengths,
                                         trials=3)
        per = bench._per_iter_seconds(times[0], lengths, flops,
                                      strict=True)
        return per * 1e3

    ms = _retry_windows(measure)
    ceil = ON_CHIP_FLOORS["flash32k_prefill_ms_max"]
    print(f"       flash 32k prefill {ms:.2f} ms/call (ceiling {ceil:g})")
    if ms > ceil:
        raise FloorError(f"flash 32k prefill {ms:.2f} ms exceeds ceiling "
                         f"{ceil:g} ms")
    return ms


def floor_megakernel_vs_jit() -> float:
    """Full-model megakernel decode step vs the jitted bare-shard ladder
    (bench.py's own rungs — same fail-loud chains) must stay under
    ON_CHIP_FLOORS['megakernel_vs_jit_max'] (2.0 -> 1.5 in round 6 with
    the cross-layer fused assembly; -> 1.0 in round 9 with the
    PREFETCH_MAT stall-slice kill — the megakernel must not lose to
    bare jit, the reference's ordering). Slow: compiles two 36-layer
    programs."""
    import bench
    from triton_distributed_tpu.obs.gate import ON_CHIP_FLOORS

    def measure():
        mk = bench._megakernel_decode_metric()["decode_step_ms_megakernel"]
        if not isinstance(mk, (int, float)):
            raise bench.BenchError(f"megakernel rung refused: {mk}")
        dec = bench._decode_step_metric()
        bare = dec.get("decode_step_ms_qwen3_8b_tp8_shard")
        if not isinstance(bare, (int, float)):
            raise bench.BenchError(
                f"jit bare rung refused: {bare!r}")
        return mk / bare, mk, bare

    ratio, mk, bare = _retry_windows(measure, attempts=2)
    ceil = ON_CHIP_FLOORS["megakernel_vs_jit_max"]
    print(f"       megakernel {mk:.3f} ms vs jit bare {bare:.3f} ms — "
          f"{ratio:.2f}x (ceiling {ceil:g}x)")
    if ratio > ceil:
        raise FloorError(f"megakernel/jit ratio {ratio:.2f} exceeds "
                         f"{ceil:g}x")
    return ratio


def run_floors(check) -> None:
    """The perf-floors section: hardware regressions can't ship silently
    (obs/gate.py ON_CHIP_FLOORS; mirrored by tests_onchip/test_floors.py).
    TDTPU_SKIP_MK_FLOOR=1 skips the slow 36-layer megakernel ratio."""
    print("\nperf floors (obs/gate.py ON_CHIP_FLOORS)")
    check("floor: GEMM TFLOP/s (pinned shape)", floor_gemm_tflops)
    check("floor: flash 32k prefill ms", floor_flash32k_ms)
    if os.environ.get("TDTPU_SKIP_MK_FLOOR"):
        print("  skip floor: megakernel vs jit (TDTPU_SKIP_MK_FLOOR)")
    else:
        check("floor: megakernel decode vs jit", floor_megakernel_vs_jit)


def main() -> int:
    if jax.default_backend() != "tpu":
        print("no TPU backend — nothing to check (tests cover interpret "
              "mode); skipping with success")
        return 0

    from triton_distributed_tpu.runtime import initialize_distributed

    ctx = initialize_distributed(mesh_shape=(1,), axis_names=("tp",))
    rng = np.random.default_rng(0)
    failures = []
    total = [0]

    def check(name, fn):
        total[0] += 1
        try:
            jax.block_until_ready(fn())
            print(f"  OK   {name}")
        except Exception as e:
            failures.append(name)
            print(f"  FAIL {name}: {type(e).__name__}: {str(e)[:140]}")
            if os.environ.get("TDTPU_CHECK_VERBOSE"):
                traceback.print_exc()

    print("kernel families on", jax.devices()[0])
    from triton_distributed_tpu.ops import (
        ag_gemm, all_gather, all_reduce, fast_all_to_all, fast_allgather,
        flash_decode, gemm_allreduce, gemm_rs, pallas_matmul, reduce_scatter,
        ring_attention, sp_ag_attention, ulysses_attention,
    )

    a = jnp.asarray(rng.standard_normal((256, 512)) * 0.1, jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((512, 256)) * 0.1, jnp.bfloat16)
    check("pallas_matmul", lambda: pallas_matmul(a, b))

    def fp8_matmul():
        a8 = a.astype(jnp.float8_e4m3fn)
        b8 = b.astype(jnp.float8_e4m3fn)
        out = pallas_matmul(a8, b8, out_dtype=jnp.float32)
        gold = np.asarray(a8.astype(jnp.float32)) @ np.asarray(
            b8.astype(jnp.float32))
        np.testing.assert_allclose(np.asarray(out), gold, rtol=1e-4,
                                   atol=1e-4)
        return out

    check("pallas_matmul fp8 (e4m3)", fp8_matmul)

    # Sub-chunk AG+GEMM: the degenerate 0-peer kernel still compiles the
    # per-(source, sub-block) semaphore waits + per-sub matmul structure.
    from jax.sharding import PartitionSpec as _P

    from triton_distributed_tpu.ops.allgather_gemm import (
        AGGemmConfig, ag_gemm_local,
    )
    from triton_distributed_tpu.runtime import shard_map_on

    def ag_gemm_sub():
        def run(a2, b2):
            return ag_gemm_local(a2, b2, axis="tp", num_ranks=1,
                                 cfg=AGGemmConfig(sub_chunks=2,
                                                  force_kernel=True))

        out = shard_map_on(ctx, run, (_P(), _P()), _P())(a, b)
        gold = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
        np.testing.assert_allclose(np.asarray(out, np.float32), gold,
                                   rtol=5e-2, atol=5e-2)
        return out

    check("ag_gemm sub-chunk (degenerate)", ag_gemm_sub)
    check("ag_gemm", lambda: ag_gemm(a, b, ctx))
    check("gemm_rs", lambda: gemm_rs(a, b, ctx))
    check("gemm_allreduce", lambda: gemm_allreduce(a, b, ctx))
    check("all_gather", lambda: all_gather(a, ctx))
    check("fast_allgather", lambda: fast_allgather(a, ctx))
    x1 = jnp.asarray(rng.standard_normal((1, 128, 256)) * 0.1, jnp.float32)
    check("all_reduce", lambda: all_reduce(x1, ctx))
    check("reduce_scatter", lambda: reduce_scatter(x1, ctx))

    # 2-D torus collectives, single-axis-degenerate (1,1) mesh: validates
    # the multi-axis dispatch + fallback contract compiles on-chip (the
    # 2-axis kernel itself needs >1 device per axis; its golden runs on
    # the virtual (2,4) mesh — tests/test_multi_axis.py).
    def torus_degenerate():
        from triton_distributed_tpu.ops import (
            all_gather_torus, all_reduce_torus, reduce_scatter_torus,
        )
        from triton_distributed_tpu.runtime.context import (
            initialize_distributed, set_context,
        )

        ctxt = initialize_distributed(mesh_shape=(1, 1),
                                      axis_names=("x", "y"))
        g = all_gather_torus(a, ctxt)
        r = all_reduce_torus(x1[:, None], ctxt)
        s = reduce_scatter_torus(x1[:, None], ctxt)
        set_context(ctx)
        return g, r, s

    check("torus collectives (degenerate 1x1)", torus_degenerate)

    q = jnp.asarray(rng.standard_normal((2, 16, 128)) * 0.1, jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 64, 8, 128)) * 0.1, jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 64, 8, 128)) * 0.1, jnp.float32)
    check("flash_decode", lambda: flash_decode(
        q, k, v, jnp.asarray([64], jnp.int32), ctx, method="pallas"))
    qs = jnp.asarray(rng.standard_normal((1, 64, 16, 128)) * 0.1, jnp.float32)
    ks = jnp.asarray(rng.standard_normal((1, 64, 8, 128)) * 0.1, jnp.float32)
    vs = jnp.asarray(rng.standard_normal((1, 64, 8, 128)) * 0.1, jnp.float32)
    check("sp_ag_attention", lambda: sp_ag_attention(qs, ks, vs, ctx,
                                                     causal=True))
    check("ring_attention", lambda: ring_attention(qs, ks, vs, ctx,
                                                   axis="tp"))
    check("ulysses_attention", lambda: ulysses_attention(qs, ks, vs, ctx))

    # Tiled flash-attention prefill (multi-tile grid + GQA + causal skip),
    # verified against the dense golden at a real tiled shape.
    from triton_distributed_tpu.ops.flash_attention import (
        _block_attn, flash_attention, flash_attention_partial,
    )

    def flash_prefill():
        qf = jnp.asarray(rng.standard_normal((1, 1024, 8, 128)) * 0.3,
                         jnp.bfloat16)
        kf = jnp.asarray(rng.standard_normal((1, 1024, 4, 128)) * 0.3,
                         jnp.bfloat16)
        vf = jnp.asarray(rng.standard_normal((1, 1024, 4, 128)) * 0.3,
                         jnp.bfloat16)
        out = flash_attention(qf, kf, vf, causal=True)
        acc, _, l = _block_attn(qf, kf, vf,
                                jnp.tril(jnp.ones((1024, 1024), bool)))
        gold = acc / jnp.maximum(l, 1e-30)[..., None]
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(gold), atol=2e-2)
        # Partial contract: rank-style offsets, hidden shard comes back dead.
        _, _, l_hidden = flash_attention_partial(qf, kf, vf, q_offset=0,
                                                 k_offset=10**6)
        assert float(jnp.max(l_hidden)) == 0.0
        return out

    check("flash_attention prefill", flash_prefill)

    send = jnp.asarray(rng.standard_normal((1, 1, 32, 128)) * 0.1, jnp.float32)
    splits = jnp.asarray(np.full((1, 1, 2), 8), jnp.int32)
    check("fast_all_to_all", lambda: fast_all_to_all(send, splits, ctx)[0])
    send8 = send.astype(jnp.float8_e4m3fn)
    check("fast_all_to_all fp8 (e4m3)",
          lambda: fast_all_to_all(send8, splits, ctx)[0])

    # Barrier-free parity-stream kernels (decode steady state): the n=1
    # degenerate grid still compiles the parity slicing, per-parity
    # semaphores, and aliased persistent workspace through Mosaic.
    from triton_distributed_tpu.ops.allreduce import (
        all_reduce_stream, ar_stream_workspace,
    )
    from triton_distributed_tpu.ops.all_to_all import (
        a2a_stream_workspace, fast_all_to_all_stream,
    )
    from triton_distributed_tpu.runtime import shard_map_on
    from jax.sharding import PartitionSpec as _P

    def ar_stream():
        xloc = jnp.asarray(rng.standard_normal((1, 64, 256)), jnp.float32)

        def run(x):
            ws, idx = ar_stream_workspace(1, 64, 256, x.dtype)
            out, ws, idx = all_reduce_stream(x[0], ws, idx, num_ranks=1,
                                             force_kernel=True)
            out, ws, idx = all_reduce_stream(out, ws, idx, num_ranks=1,
                                             force_kernel=True)
            return out[None]

        out = shard_map_on(ctx, run, _P("tp"), _P("tp"))(xloc)
        np.testing.assert_allclose(np.asarray(out), np.asarray(xloc),
                                   rtol=1e-6)
        return out

    check("all_reduce_stream (parity)", ar_stream)

    def a2a_stream():
        sb = jnp.asarray(rng.standard_normal((1, 1, 32, 128)), jnp.float32)
        sp = jnp.asarray(np.full((1, 1, 2), 8), jnp.int32)

        def run(sb, sp):
            ws, idx = a2a_stream_workspace(1, 32, 128, sb.dtype)
            rb, rs, ws, idx = fast_all_to_all_stream(
                sb[0], sp[0], ws, idx, num_ranks=1, force_kernel=True)
            rb, rs, ws, idx = fast_all_to_all_stream(
                rb, rs, ws, idx, num_ranks=1, force_kernel=True)
            return rb[None]

        out = shard_map_on(ctx, run, (_P("tp"), _P("tp")), _P("tp"))(sb, sp)
        np.testing.assert_allclose(np.asarray(out)[0, 0, :16],
                                   np.asarray(sb)[0, 0, :16], rtol=0)
        return out

    check("fast_all_to_all_stream (parity)", a2a_stream)

    from triton_distributed_tpu.ops.allgather import (
        ag_stream_workspace, all_gather_stream,
    )

    def ag_stream():
        xloc = jnp.asarray(rng.standard_normal((1, 64, 256)), jnp.float32)

        def run(x):
            ws, idx = ag_stream_workspace(1, 64, 256, x.dtype)
            out, ws, idx = all_gather_stream(x[0], ws, idx, num_ranks=1,
                                             force_kernel=True)
            out2, ws, idx = all_gather_stream(out[:64], ws, idx,
                                              num_ranks=1,
                                              force_kernel=True)
            return out2[None]

        out = shard_map_on(ctx, run, _P("tp"), _P("tp"))(xloc)
        np.testing.assert_allclose(np.asarray(out)[0], np.asarray(xloc)[0],
                                   rtol=1e-6)
        return out

    check("all_gather_stream (parity)", ag_stream)

    # Fused GEMM+AR stream (chunked partials pushed while the next chunk
    # computes): the n=1 degenerate grid still compiles the per-chunk
    # matmul-into-slot, nbi-push bookkeeping, parity slicing, and slot
    # reduction through Mosaic.
    from triton_distributed_tpu.ops.gemm_allreduce import (
        gemm_ar_stream, gemm_ar_stream_workspace,
    )

    def gemm_ar_fused():
        af = jnp.asarray(rng.standard_normal((8, 512)) * 0.1, jnp.bfloat16)
        bf = jnp.asarray(rng.standard_normal((512, 512)) * 0.1, jnp.bfloat16)

        def run(a2, b2):
            ws, idx = gemm_ar_stream_workspace(1, a2.shape[0], b2.shape[1],
                                               a2.dtype)
            out, ws, idx = gemm_ar_stream(a2, b2, ws, idx, axis="tp",
                                          num_ranks=1, force_kernel=True)
            out2, ws, idx = gemm_ar_stream(a2, b2, ws, idx, axis="tp",
                                           num_ranks=1, force_kernel=True)
            return out2

        out = shard_map_on(ctx, run, (_P(), _P()), _P())(af, bf)
        gold = np.asarray(af, np.float32) @ np.asarray(bf, np.float32)
        np.testing.assert_allclose(np.asarray(out, np.float32), gold,
                                   rtol=5e-2, atol=5e-2)
        return out

    check("gemm_ar_stream (fused, degenerate)", gemm_ar_fused)

    # P2P transport family (the one r5 kernel family with no on-chip
    # gate — ISSUE 4 satellite): ring shift (the collapsed send/recv
    # pair), arbitrary-pair permute (per-pair semaphores), and the PP
    # CommOp ping-pong on top. force_kernel compiles the real kernels at
    # n=1 as self-push loopback, like the parity streams above.
    from triton_distributed_tpu.layers.pp import CommOp
    from triton_distributed_tpu.ops.p2p import (
        p2p_permute_local, p2p_shift_local,
    )

    xp = jnp.asarray(rng.standard_normal((256, 256)), jnp.float32)

    def p2p_send_recv():
        def run(xl):
            return p2p_shift_local(xl, shift=1, axis="tp", num_ranks=1,
                                   force_kernel=True)

        out = shard_map_on(ctx, run, _P(), _P())(xp)
        # n=1 self-loopback: the shifted ring delivers x back to rank 0.
        np.testing.assert_allclose(np.asarray(out), np.asarray(xp), rtol=0)
        return out

    check("p2p_send/p2p_recv (ring shift, degenerate)", p2p_send_recv)

    def p2p_permute_pair():
        def run(xl):
            return p2p_permute_local(xl, [(0, 0)], axis="tp", num_ranks=1,
                                     force_kernel=True)

        out = shard_map_on(ctx, run, _P(), _P())(xp)
        np.testing.assert_allclose(np.asarray(out), np.asarray(xp), rtol=0)
        return out

    check("p2p_permute (per-pair semaphores, degenerate)", p2p_permute_pair)

    def commop_pingpong():
        op = CommOp(axis="tp", num_ranks=1, force_kernel=True)

        def run(xl):
            y = op.send(xl, 0, 0)      # ping
            return op.send(y, 0, 0)    # pong

        out = shard_map_on(ctx, run, _P(), _P())(xp)
        np.testing.assert_allclose(np.asarray(out), np.asarray(xp), rtol=0)
        return out

    check("CommOp ping-pong (layers/pp.py)", commop_pingpong)

    # Paged-KV attention (page-table scalar prefetch + per-page DMA).
    from triton_distributed_tpu.ops import (
        init_paged_kv_cache, paged_append, paged_decode_attention,
    )

    def paged():
        cache = init_paged_kv_cache(2, num_pages=8, page_size=16,
                                    num_kv_heads=8, head_dim=128,
                                    max_pages=4)
        for _ in range(20):
            cache = paged_append(
                cache,
                jnp.asarray(rng.standard_normal((2, 8, 128)), jnp.float32),
                jnp.asarray(rng.standard_normal((2, 8, 128)), jnp.float32))
        qq = jnp.asarray(rng.standard_normal((2, 8, 128)), jnp.float32)
        return paged_decode_attention(qq, cache)

    check("paged_decode_attention", paged)

    # MegaKernel: a full decode step in one launch (fp32 + bf16).
    from triton_distributed_tpu.megakernel.models import (
        broadcast_rows, build_decode_step, feed_layer_weights, rope_tables,
    )
    from triton_distributed_tpu.megakernel.tasks import TILE, MatHandle

    def mega(dtype):
        hidden, hq, hkv, ffn, S, pos = 256, 2, 1, 256, 256, 100
        prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                                 ffn_local=ffn, num_layers=1, max_seq=S,
                                 pos=pos, num_ranks=1, inkernel_append=True)
        comp = prog.mb.compile(dtype=dtype)
        h = prog.layers[0]
        cos, sin = rope_tables(pos, TILE, 1e6)
        ones = np.ones(hidden, np.float32)
        feeds = {prog.x: rng.standard_normal((TILE, hidden)).astype(np.float32),
                 prog.cos: cos, prog.sin: sin,
                 h.attn_norm: broadcast_rows(ones),
                 h.mlp_norm: broadcast_rows(ones),
                 h.q_norm: broadcast_rows(np.ones(TILE, np.float32)),
                 h.k_norm: broadcast_rows(np.ones(TILE, np.float32))}
        feed_layer_weights(
            feeds, h,
            wq=rng.standard_normal((hidden, hq * TILE)) * 0.05,
            wk=rng.standard_normal((hidden, hkv * TILE)) * 0.05,
            wv=rng.standard_normal((hidden, hkv * TILE)) * 0.05,
            wo=rng.standard_normal((hq * TILE, hidden)) * 0.05,
            w_gate=rng.standard_normal((hidden, ffn)) * 0.05,
            w_up=rng.standard_normal((hidden, ffn)) * 0.05,
            w_down=rng.standard_normal((ffn, hidden)) * 0.05)
        for tk, tv in zip(h.kT, h.v):
            feeds[tk] = rng.standard_normal((TILE, S)) * 0.3
            feeds[tv] = rng.standard_normal((S, TILE)) * 0.3
        feeds = {kk_: (tuple(jnp.asarray(np.asarray(x_, np.float32))
                             for x_ in vv_) if isinstance(vv_, tuple)
                       else jnp.asarray(np.asarray(vv_, np.float32)))
                 for kk_, vv_ in feeds.items()}
        (out,) = comp.run(feeds, outputs=[prog.x_out])
        assert np.isfinite(np.asarray(out, np.float32)).all()
        return out

    check("megakernel decode step (fp32)", lambda: mega(jnp.float32))
    check("megakernel decode step (bf16)", lambda: mega(jnp.bfloat16))

    # fp8 weight workspace: GEMM_WIDE_W8 + PREFETCH_W8 stream e4m3 weight
    # tiles (half the bytes) and upcast in VMEM.
    from triton_distributed_tpu.megakernel import MegaKernelBuilder

    def mega_fp8():
        mb = MegaKernelBuilder()
        x8 = mb.tensor(TILE, 2 * TILE)
        w8 = mb.tensor(2 * TILE, 3 * TILE, fp8=True)
        out8 = mb.tensor(TILE, 3 * TILE)
        mb.prefetch(w8.tile(0, 0), fp8=True)
        mb.gemm(out8, x8, w8, prefetch_first=True, width=3)
        comp = mb.compile(dtype=jnp.bfloat16)
        ax = rng.standard_normal((TILE, 2 * TILE)).astype(np.float32)
        aw = rng.standard_normal((2 * TILE, 3 * TILE)).astype(np.float32) * 0.1
        (res,) = comp.run({x8: jnp.asarray(ax), w8: jnp.asarray(aw)},
                          outputs=[out8])
        wq = np.asarray(jnp.asarray(aw).astype(jnp.float8_e4m3fn)
                        .astype(jnp.float32))
        np.testing.assert_allclose(np.asarray(res, np.float32), ax @ wq,
                                   rtol=5e-2, atol=5e-2)
        return res

    check("megakernel fp8 weight workspace", mega_fp8)

    # In-kernel paged-attention task: page table in queue DATA rows, DMA
    # addresses read from SMEM per step.
    from triton_distributed_tpu.megakernel.tasks import TILE as MTILE

    def mega_paged():
        mb = MegaKernelBuilder()
        q = mb.tensor(MTILE, MTILE)
        out = mb.tensor(MTILE, MTILE)
        kt_pages = [mb.tensor(MTILE, MTILE) for _ in range(3)]
        v_pages = [mb.tensor(MTILE, MTILE) for _ in range(3)]
        pages = [(kt_pages[j].tile(0, 0), v_pages[j].tile(0, 0))
                 for j in range(3)]
        mb.attn_decode_paged(out, q, pages, valid_len=2 * MTILE + 40,
                             scale=MTILE ** -0.5)
        comp = mb.compile()
        feeds = {q: rng.standard_normal((MTILE, MTILE)) * 0.3}
        for j in range(3):
            feeds[kt_pages[j]] = rng.standard_normal((MTILE, MTILE)) * 0.3
            feeds[v_pages[j]] = rng.standard_normal((MTILE, MTILE)) * 0.3
        feeds = {h: jnp.asarray(np.asarray(v_, np.float32))
                 for h, v_ in feeds.items()}
        (res,) = comp.run(feeds, outputs=[out])
        assert np.isfinite(np.asarray(res)).all()
        return res

    check("megakernel paged-attention task", mega_paged)

    # MoE tasks: MOE_TOPK (in-VMEM top-k + softmax) + MOE_FFN — the FFN's
    # inactive-expert skip is a DATA-DEPENDENT pl.when on a vector-reduced
    # scalar, the one construct in the MoE design Mosaic could reject;
    # this gate is its on-chip proof.
    def mega_moe():
        from triton_distributed_tpu.megakernel.models import (
            build_decode_step, rope_tables,
        )

        E, topk, ffn_l, hid = 8, 2, 256, 256
        progm = build_decode_step(
            hidden=hid, hq_local=1, hkv_local=1, ffn_local=ffn_l,
            num_layers=1, max_seq=256, pos=100, num_ranks=1,
            moe_experts=E, moe_topk=topk, batch=4)
        compm = progm.mb.compile()
        hm = progm.layers[0]
        cosf, sinf = rope_tables(100, MTILE, 1e6)
        feeds = {progm.x: rng.standard_normal((MTILE, hid)) * 0.3,
                 progm.cos: cosf, progm.sin: sinf}
        import dataclasses as _dc

        for f in _dc.fields(hm):
            h_ = getattr(hm, f.name)
            if f.name in ("w_gate", "w_up", "w_down") or h_ is None:
                continue
            if isinstance(h_, list):
                for hh in h_:
                    feeds[hh] = rng.standard_normal(
                        (hh.rows, hh.cols)) * 0.1
            elif isinstance(h_, MatHandle):
                feeds[h_] = (tuple(rng.standard_normal((h_.k, h_.n)) * 0.1
                                   for _ in range(2)) if h_.pair
                             else rng.standard_normal((h_.k, h_.n)) * 0.1)
            else:
                feeds[h_] = rng.standard_normal((h_.rows, h_.cols)) * 0.1
        feeds = {h_: jnp.asarray(np.asarray(v_, np.float32))
                 for h_, v_ in feeds.items()}
        (res,) = compm.run(feeds, outputs=[progm.x_out])
        assert np.isfinite(np.asarray(res)).all()
        return res

    check("megakernel MoE decode (topk + expert-skip FFN)", mega_moe)

    # Forced in-kernel AR at n=1 (the round-6 cross-device rung's pricing
    # mode): ALLREDUCE_ROW runs the full loopback protocol — remote
    # self-push, delivery wait, slab reduce — the one new Mosaic surface
    # of the rung. Token-identical to the AR-free program (AR of 1 rank
    # is identity).
    def mega_forced_ar():
        from triton_distributed_tpu.megakernel.models import (
            build_decode_step, rope_tables,
        )

        hidden, hq, hkv, ffn, S, pos = 256, 2, 1, 256, 256, 100
        rng2 = np.random.default_rng(7)

        def build(force):
            prog = build_decode_step(
                hidden=hidden, hq_local=hq, hkv_local=hkv, ffn_local=ffn,
                num_layers=1, max_seq=S, pos=pos, num_ranks=1,
                force_ar_tasks=force)
            comp = prog.mb.compile(dtype=jnp.bfloat16, force_ar=force)
            h = prog.layers[0]
            cos, sin = rope_tables(pos, TILE, 1e6)
            feeds = {prog.x: rng2.standard_normal((TILE, hidden)) * 0.3,
                     prog.cos: cos, prog.sin: sin,
                     h.attn_norm: broadcast_rows(np.ones(hidden, np.float32)),
                     h.mlp_norm: broadcast_rows(np.ones(hidden, np.float32)),
                     h.q_norm: broadcast_rows(np.ones(TILE, np.float32)),
                     h.k_norm: broadcast_rows(np.ones(TILE, np.float32))}
            feed_layer_weights(
                feeds, h,
                wq=rng2.standard_normal((hidden, hq * TILE)) * 0.05,
                wk=rng2.standard_normal((hidden, hkv * TILE)) * 0.05,
                wv=rng2.standard_normal((hidden, hkv * TILE)) * 0.05,
                wo=rng2.standard_normal((hq * TILE, hidden)) * 0.05,
                w_gate=rng2.standard_normal((hidden, ffn)) * 0.05,
                w_up=rng2.standard_normal((hidden, ffn)) * 0.05,
                w_down=rng2.standard_normal((ffn, hidden)) * 0.05)
            for tk, tv in zip(h.kT, h.v):
                feeds[tk] = rng2.standard_normal((TILE, S)) * 0.3
                feeds[tv] = rng2.standard_normal((S, TILE)) * 0.3
            feeds = {kk_: (tuple(jnp.asarray(np.asarray(x_, np.float32))
                                 for x_ in vv_) if isinstance(vv_, tuple)
                           else jnp.asarray(np.asarray(vv_, np.float32)))
                     for kk_, vv_ in feeds.items()}
            return prog, comp, feeds

        rng2 = np.random.default_rng(7)
        prog_a, comp_a, feeds_a = build(False)
        base = np.asarray(comp_a.run(feeds_a, outputs=[prog_a.x_out])[0],
                          np.float32)
        rng2 = np.random.default_rng(7)
        prog_b, comp_b, feeds_b = build(True)

        def run_forced(*vals):
            keys = list(feeds_b.keys())
            feeds = {k_: v_ for k_, v_ in zip(keys, vals)}
            return comp_b.run(feeds, outputs=[prog_b.x_out])[0]

        vals = list(feeds_b.values())
        out = shard_map_on(ctx, run_forced,
                           tuple(_P() for _ in vals), _P())(*vals)
        np.testing.assert_allclose(np.asarray(out, np.float32), base,
                                   rtol=5e-2, atol=5e-2)
        return out

    check("megakernel forced in-kernel AR (n=1 loopback)", mega_forced_ar)

    if os.environ.get("TDTPU_SKIP_FLOORS"):
        print("\nperf floors skipped (TDTPU_SKIP_FLOORS)")
    else:
        run_floors(check)

    if failures:
        print(f"\n{total[0] - len(failures)}/{total[0]} passed — "
              f"{len(failures)} FAILURES: {failures}")
        return 1
    print(f"\n{total[0]}/{total[0]}: all kernel-family + perf-floor "
          "gates pass on real TPU")
    return 0


if __name__ == "__main__":
    sys.exit(main())
