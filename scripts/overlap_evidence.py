#!/usr/bin/env python
"""Measured (not asserted) AG+GEMM overlap evidence — VERDICT r3 #10.

Single-chip constraint: no ICI peer exists, so the producer's remote DMA
is stood in by the SAME kernel's real HBM→HBM shard copy (the n=1
degenerate ag_gemm kernel copies the shard into the workspace through the
same async DMA engines a remote push would use, and the consumer waits
the same per-sub-chunk semaphores). If the fused kernel's copy did NOT
overlap the MXU, its time would be >= copy + matmul run separately; the
measured ratio below is the overlap evidence, scripted and fail-loud.

    t_seq   = t(copy kernel) + t(matmul kernel)      (separate launches)
    t_fused = t(ag_gemm n=1 force_kernel, sub_chunks=4)
    overlap_saved = t_seq - t_fused   (> 0 = the DMA hid under compute)

Prints ONE JSON line. Methodology: chain-differential + interleaved +
min-of-passes (bench.py header; the only trustworthy timing here).
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P


def copy_kernel(x):
    """Whole-array HBM→HBM copy through the DMA engine (the AG stand-in)."""
    def kern(x_ref, o_ref, sem):
        cp = pltpu.make_async_copy(x_ref, o_ref, sem)
        cp.start()
        cp.wait()

    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA(())],
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
    )(x)


def main():
    assert jax.default_backend() == "tpu", "evidence needs the real chip"
    from triton_distributed_tpu.ops.allgather_gemm import (
        AGGemmConfig, ag_gemm_local,
    )
    from triton_distributed_tpu.ops.gemm import pallas_matmul
    from triton_distributed_tpu.runtime import (
        initialize_distributed, shard_map_on,
    )

    ctx = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                 devices=jax.devices()[:1])
    # Copy-heavy shape: the shard copy is ~1/3 of the matmul time, so a
    # hidden copy is well above timing noise; sized so even the bare-copy
    # chain differential clears the relay's ±50ms dispatch swing.
    m, k, nc = 8192, 5120, 640
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((m, k)) * 0.05, jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((k, nc)) * 0.05, jnp.bfloat16)

    cfg = AGGemmConfig(sub_chunks=4, force_kernel=True)

    def fused(xv, wv):
        return shard_map_on(
            ctx, lambda a, b: ag_gemm_local(a, b, axis="tp", num_ranks=1,
                                            cfg=cfg),
            (P(), P()), P())(xv, wv)

    def seq(xv, wv):
        return pallas_matmul(copy_kernel(xv), wv, tile_m=cfg.tile_m,
                             tile_n=cfg.tile_n, tile_k=cfg.tile_k)

    def copy_only(xv, wv):
        return copy_kernel(xv)

    def matmul_only(xv, wv):
        return pallas_matmul(xv, wv, tile_m=cfg.tile_m, tile_n=cfg.tile_n,
                             tile_k=cfg.tile_k)

    def chain(fn, xv, wv, n):
        # REAL loop-carried dependency (c scaled to numerical nothing):
        # a `c * 0.0` coupling lets XLA hoist the loop-invariant call and
        # run the kernel once regardless of chain length.
        def body(i, c):
            out = fn(xv + (c * 1e-30).astype(xv.dtype), wv)
            return jnp.sum(out.astype(jnp.float32))

        return jax.lax.fori_loop(0, n, body, jnp.float32(0))

    fns = {name: jax.jit(functools.partial(chain, f), static_argnums=2)
           for name, f in [("fused", fused), ("seq", seq),
                           ("copy", copy_only), ("matmul", matmul_only)]}
    lengths = (16, 160)

    def timed(name, n):
        t0 = time.perf_counter()
        _ = np.asarray(fns[name](x, w, n))
        return time.perf_counter() - t0

    for name in fns:
        for n in lengths:
            timed(name, n)
    best = {(name, n): float("inf") for name in fns for n in lengths}
    for p in range(2):
        for _ in range(3):
            for name in fns:
                for n in lengths:
                    best[(name, n)] = min(best[(name, n)], timed(name, n))
        if p == 0:
            time.sleep(3)
    n1, n2 = lengths
    per = {name: (best[(name, n2)] - best[(name, n1)]) / (n2 - n1)
           for name in fns}
    if min(per.values()) <= 0:
        raise RuntimeError("non-positive differential — noisy window, rerun")
    t_seq = per["copy"] + per["matmul"]
    result = {
        "metric": "ag_gemm_overlap_evidence",
        "copy_ms": round(per["copy"] * 1e3, 3),
        "matmul_ms": round(per["matmul"] * 1e3, 3),
        "seq_kernels_ms": round(per["seq"] * 1e3, 3),
        "fused_ms": round(per["fused"] * 1e3, 3),
        "overlap_saved_ms": round((t_seq - per["fused"]) * 1e3, 3),
        "overlap_ratio": round(t_seq / per["fused"], 4),
        "note": "n=1: the shard's HBM DMA (the remote-push stand-in) "
                "hides under the consumer MXU loop iff overlap_ratio > 1",
    }
    print(json.dumps(result))
    return 0 if per["fused"] < t_seq else 1


if __name__ == "__main__":
    sys.exit(main())
