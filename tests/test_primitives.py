"""Tests for the device-side primitive layer (language/).

Mirrors the reference API-surface tests ``test_distributed_wait.py``,
``test_notify.py``, ``test_nvshmem_api.py`` (SURVEY.md §4): each primitive is
exercised in a minimal Pallas kernel on the 8-device mesh and compared against
an analytically known result.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import kernel_call, any_spec, smem_spec
from triton_distributed_tpu.runtime import shard_map_on


def test_rank_num_ranks(ctx):
    def kernel(out_ref):
        out_ref[0] = dl.rank("tp")
        out_ref[1] = dl.num_ranks("tp")

    def f():
        return kernel_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((2,), jnp.int32),
            out_specs=smem_spec(),
        )()

    out = shard_map_on(ctx, f, in_specs=(), out_specs=P("tp"))()
    out = np.asarray(out).reshape(8, 2)
    assert list(out[:, 0]) == list(range(8))
    assert all(out[:, 1] == 8)


def test_put_ring(ctx):
    """Each rank pushes its block to the right neighbor (p2p.py:31 analog)."""

    def kernel(in_ref, out_ref, send_sem, recv_sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        dst = jax.lax.rem(me + 1, n)
        rdma = shmem.putmem_nbi_block(in_ref, out_ref, send_sem, recv_sem, dst)
        rdma.wait()

    def f(x):
        return kernel_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[any_spec()],
            out_specs=any_spec(),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128)
    y = shard_map_on(ctx, f, in_specs=P("tp"), out_specs=P("tp"))(x)
    expected = np.roll(np.asarray(x).reshape(8, 1, 128), 1, axis=0).reshape(8, 128)
    np.testing.assert_allclose(np.asarray(y), expected)


def test_notify_wait(ctx):
    """Producer/consumer via notify/wait (reference test_notify.py analog):
    every rank signals every peer, then waits for all signals."""

    def kernel(out_ref, sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")

        def body(i, _):
            dl.notify(sem, jax.lax.rem(me + 1 + i, n), inc=1)
            return 0

        jax.lax.fori_loop(0, n - 1, body, 0)
        dl.wait(sem, 7)
        out_ref[0] = me

    def f():
        return kernel_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1,), jnp.int32),
            out_specs=smem_spec(),
            scratch_shapes=[pltpu.SemaphoreType.REGULAR],
        )()

    out = shard_map_on(ctx, f, in_specs=(), out_specs=P("tp"))()
    assert list(np.asarray(out)) == list(range(8))


def test_barrier_all(ctx):
    def kernel(out_ref):
        shmem.barrier_all("tp")
        out_ref[0] = dl.rank("tp")

    def f():
        return kernel_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1,), jnp.int32),
            out_specs=smem_spec(),
            uses_barrier=True,
        )()

    out = shard_map_on(ctx, f, in_specs=(), out_specs=P("tp"))()
    assert list(np.asarray(out)) == list(range(8))


def test_putmem_signal(ctx):
    """put + user-semaphore signal ordering (putmem_signal_nbi_block)."""

    def kernel(in_ref, out_ref, send_sem, recv_sem, sig):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        dst = jax.lax.rem(me + 1, n)
        rdma = shmem.putmem_signal_nbi_block(in_ref, out_ref, send_sem, recv_sem,
                                             dst)
        # The recv semaphore IS the signal: it fires only after payload
        # delivery. Receiver-side forwarding to a user semaphore keeps
        # signal-after-data ordering.
        rdma.wait_recv()
        pltpu.semaphore_signal(sig, inc=1)
        pltpu.semaphore_wait(sig, 1)

    def f(x):
        return kernel_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[any_spec()],
            out_specs=any_spec(),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.REGULAR,
            ],
        )(x)

    x = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128) * 2.0
    y = shard_map_on(ctx, f, in_specs=P("tp"), out_specs=P("tp"))(x)
    expected = np.roll(np.asarray(x).reshape(8, 1, 128), 1, axis=0).reshape(8, 128)
    np.testing.assert_allclose(np.asarray(y), expected)


def test_symm_buffers(ctx):
    from triton_distributed_tpu.runtime import symm_zeros

    buf = symm_zeros(ctx, (64, 128), jnp.bfloat16)
    assert buf.shape == (8, 64, 128)
    assert buf.dtype == jnp.bfloat16
    # one shard per device
    assert len(buf.addressable_shards) == 8
    assert buf.addressable_shards[0].data.shape == (1, 64, 128)


def test_broadcast(ctx):
    """Root pushes its block to every rank (NVSHMEM broadcast analog)."""
    root = 2

    def kernel(in_ref, out_ref, send_sems, recv_sem):
        shmem.broadcast(in_ref, out_ref, root, send_sems, recv_sem, axis="tp")

    def f(x):
        return kernel_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[any_spec()],
            out_specs=any_spec(),
            scratch_shapes=[pltpu.SemaphoreType.DMA((7,)),
                            pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 2 * 128, dtype=jnp.float32).reshape(8, 2, 128)
    out = shard_map_on(ctx, f, in_specs=(P("tp"),), out_specs=P("tp"))(x)
    out = np.asarray(out).reshape(8, 2, 128)
    for r in range(8):
        np.testing.assert_array_equal(out[r], np.asarray(x)[root])


def test_fcollect(ctx):
    """SHMEM-level all-gather into the symmetric destination (fcollect)."""

    def kernel(in_ref, out_ref, send_sems, recv_sem):
        shmem.fcollect(in_ref, out_ref, send_sems, recv_sem, axis="tp")

    def f(x):
        return kernel_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8 * x.shape[0], x.shape[1]), x.dtype),
            in_specs=[any_spec()],
            out_specs=any_spec(),
            scratch_shapes=[pltpu.SemaphoreType.DMA((7,)),
                            pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 2 * 128, dtype=jnp.float32).reshape(8 * 2, 128)
    out = shard_map_on(ctx, f, in_specs=(P("tp"),), out_specs=P("tp"))(x)
    out = np.asarray(out).reshape(8, 16, 128)
    for r in range(8):
        np.testing.assert_array_equal(out[r], np.asarray(x))


def test_getmem_emulated(ctx):
    """Pull-emulation entry point delegates to fcollect (two-sided rewrite)."""

    def kernel(in_ref, out_ref, send_sems, recv_sem):
        shmem.getmem_emulated(out_ref, in_ref, send_sems, recv_sem, axis="tp")

    def f(x):
        return kernel_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8 * x.shape[0], x.shape[1]), x.dtype),
            in_specs=[any_spec()],
            out_specs=any_spec(),
            scratch_shapes=[pltpu.SemaphoreType.DMA((7,)),
                            pltpu.SemaphoreType.DMA(())],
        )(x)

    x = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128)
    out = shard_map_on(ctx, f, in_specs=(P("tp"),), out_specs=P("tp"))(x)
    out = np.asarray(out).reshape(8, 8, 128)
    for r in range(8):
        np.testing.assert_array_equal(out[r], np.asarray(x))
