"""runtime/utils.py coverage: perf_func stats, group_profile,
merge_profiles (pid-offset disambiguation, .json.gz handling, empty-dir
behavior, host-span source kind) — ISSUE 3 satellite (none of this was
tested before)."""

import gzip
import json
import os

import jax.numpy as jnp
import pytest

from triton_distributed_tpu.runtime.utils import (
    PerfStats, group_profile, merge_profiles, perf_func,
)


# ---------------------------------------------------------------------------
# perf_func
# ---------------------------------------------------------------------------

def test_perf_func_returns_stats_and_mean_float():
    out, stats = perf_func(lambda: jnp.arange(8) * 2, iters=5,
                           warmup_iters=1)
    assert jnp.array_equal(out, jnp.arange(8) * 2)
    # Backward compatible: the stats object IS the mean in ms.
    assert isinstance(stats, float)
    assert isinstance(stats, PerfStats)
    assert len(stats.samples) == 5
    assert stats.mean == pytest.approx(sum(stats.samples) / 5)
    assert float(stats) == stats.mean
    # Percentile/extreme consistency.
    assert stats.min <= stats.p50 <= stats.p95 <= stats.max
    assert stats.min == min(stats.samples)
    assert 2 * stats > 0  # arithmetic like any float


def test_perf_stats_percentiles_exact():
    st = PerfStats([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 9.0, 10.0])
    assert st.p50 == 5.0     # nearest-rank: ceil(0.5*10) = 5th value
    assert st.p95 == 10.0
    assert st.min == 1.0
    assert float(st) == pytest.approx(5.5)
    with pytest.raises(ValueError):
        PerfStats([])


def test_perf_stats_pickle_and_deepcopy():
    """float subclass round-trips: the default float reduce would rebuild
    via cls(mean) and crash __new__."""
    import copy
    import pickle

    st = PerfStats([1.0, 3.0])
    for st2 in (pickle.loads(pickle.dumps(st)), copy.deepcopy(st)):
        assert float(st2) == 2.0
        assert st2.samples == (1.0, 3.0)
        assert st2.p95 == 3.0


# ---------------------------------------------------------------------------
# group_profile
# ---------------------------------------------------------------------------

def test_group_profile_disabled_is_noop(tmp_path):
    with group_profile("x", do_prof=False, log_dir=str(tmp_path)):
        pass
    assert list(tmp_path.iterdir()) == []
    with group_profile(None, do_prof=True, log_dir=str(tmp_path)):
        pass
    assert list(tmp_path.iterdir()) == []


# ---------------------------------------------------------------------------
# merge_profiles
# ---------------------------------------------------------------------------

def _fake_trace(path, pid=7, name="proc", gz=False):
    data = {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": pid,
         "args": {"name": name}},
        {"name": "work", "ph": "X", "pid": pid, "tid": 1, "ts": 1.0,
         "dur": 2.0},
    ]}
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if gz:
        with gzip.open(path, "wt") as f:
            json.dump(data, f)
    else:
        with open(path, "w") as f:
            json.dump(data, f)


def test_merge_profiles_empty_dir_skips_writing(tmp_path):
    d = tmp_path / "empty"
    d.mkdir()
    out = tmp_path / "merged.json"
    with pytest.warns(RuntimeWarning, match="no trace sources"):
        n = merge_profiles([str(d)], str(out))
    assert n == 0
    assert not out.exists()   # no empty merge shipped


def test_merge_profiles_missing_dir_warns(tmp_path):
    out = tmp_path / "merged.json"
    with pytest.warns(RuntimeWarning):
        n = merge_profiles([str(tmp_path / "nope")], str(out))
    assert n == 0
    assert not out.exists()


def test_merge_profiles_pid_offsets_and_gz(tmp_path):
    # Two source dirs, one .json + one .json.gz, identical pids: the merge
    # must disambiguate pids per source and tag the process names.
    _fake_trace(str(tmp_path / "h0" / "a.trace.json"), pid=7, name="host0")
    _fake_trace(str(tmp_path / "h1" / "b.trace.json.gz"), pid=7,
                name="host1", gz=True)
    out = tmp_path / "merged.json"
    n = merge_profiles([str(tmp_path / "h0"), str(tmp_path / "h1")],
                       str(out))
    assert n == 2
    with open(out) as f:
        merged = json.load(f)["traceEvents"]
    pids = sorted({e["pid"] for e in merged})
    assert pids == [100_007, 200_007]   # (d_i + 1) * 100_000 offsets
    names = {e["args"]["name"] for e in merged
             if e.get("name") == "process_name"}
    assert names == {"[a] host0", "[b] host1"}


def test_merge_profiles_gz_output(tmp_path):
    _fake_trace(str(tmp_path / "h0" / "a.trace.json"))
    out = tmp_path / "merged.json.gz"
    assert merge_profiles([str(tmp_path / "h0")], str(out)) == 1
    with gzip.open(out, "rt") as f:
        assert len(json.load(f)["traceEvents"]) == 2


def test_merge_profiles_accepts_host_span_files(tmp_path):
    """The obs tracer's *.spans.json is a first-class source kind: host
    and device lanes merge into one Perfetto view."""
    from triton_distributed_tpu.obs.trace import Tracer

    import time as _time

    t = Tracer(run_dir=str(tmp_path / "run"), name="host")
    t0 = _time.perf_counter_ns()
    t._emit_complete("engine.prefill", t0, t0 + 5000, {"batch": 1})
    span_path = t.save()
    assert span_path.endswith("host.spans.json")
    _fake_trace(str(tmp_path / "run" / "dev.trace.json"), pid=3,
                name="device")
    out = tmp_path / "merged.json"
    n = merge_profiles([str(tmp_path / "run")], str(out))
    assert n == 2
    with open(out) as f:
        names = {e.get("name") for e in json.load(f)["traceEvents"]}
    assert "engine.prefill" in names and "work" in names
