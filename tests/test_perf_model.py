"""Perf-model tests: sanity, monotonicity, crossovers, pruning fidelity.

Reference analog: the reference never unit-tests gemm_perf_model/-comm_perf_model
directly, but its auto-selectors depend on them; here the selector logic is
model-driven so the model gets first-class tests.
"""

import numpy as np
import pytest

from triton_distributed_tpu.runtime import perf_model as pm


SPEC = pm.chip_spec("TPU v5p")


def test_chip_spec_detection():
    assert pm.chip_spec("TPU v4").name == "v4"
    assert pm.chip_spec("TPU v5p").name == "v5p"
    assert pm.chip_spec("TPU v5e").name == "v5e"
    assert pm.chip_spec("TPU v6e").name == "v6e"
    assert pm.chip_spec("TPU v5 lite").name == "v5e"
    # Unknown hardware falls back to a generic self-consistent spec.
    assert pm.chip_spec("cpu").name == "generic"


def test_gemm_time_monotone_and_quantized():
    t1 = pm.gemm_time_s(1024, 1024, 1024, 2, SPEC)
    t2 = pm.gemm_time_s(2048, 1024, 1024, 2, SPEC)
    assert t2 > t1 > 0
    # MXU quantization: 129 rows costs the same compute as 256.
    assert pm.gemm_time_s(129, 2048, 2048, 2, SPEC) == pytest.approx(
        pm.gemm_time_s(256, 2048, 2048, 2, SPEC), rel=0.2)


def test_gemm_tflops_below_peak():
    tf = pm.gemm_tflops(4096, 4096, 4096, 2, SPEC)
    assert 0 < tf <= SPEC.bf16_tflops


def test_collectives_monotone_in_bytes_and_ranks():
    for fn in (pm.allgather_ring_time_s, pm.allgather_full_mesh_time_s,
               pm.reduce_scatter_ring_time_s):
        assert fn(1 << 24, 8, SPEC) > fn(1 << 20, 8, SPEC) > 0
        assert fn(1 << 24, 8, SPEC) > fn(1 << 24, 4, SPEC)
        assert fn(123, 1, SPEC) == 0.0


def test_ag_method_crossover_exists():
    """Small payloads → full-mesh (latency); huge → ring (bandwidth)."""
    from triton_distributed_tpu.ops.allgather import (
        AllGatherMethod,
        get_auto_all_gather_method,
    )

    small = get_auto_all_gather_method(8 * 1024, 8)
    assert small == AllGatherMethod.FULL_MESH_PUSH
    # At some payload the ring must win (full-mesh sends (n-1)x the bytes
    # through finite egress; ring pipelines them) — including on a small
    # n=4 axis, where the mean-hop-distance term decides the tie.
    for n in (4, 8):
        methods = {get_auto_all_gather_method(1 << s, n) for s in range(13, 31)}
        assert AllGatherMethod.RING_1D in methods, n


def test_ar_method_crossover_exists():
    from triton_distributed_tpu.ops.allreduce import (
        AllReduceMethod,
        get_auto_allreduce_method,
    )

    assert get_auto_allreduce_method(4 * 1024, 8) == AllReduceMethod.ONE_SHOT
    methods = {get_auto_allreduce_method(1 << s, 8) for s in range(13, 31)}
    assert AllReduceMethod.TWO_SHOT in methods


def test_allreduce_two_shot_beats_one_shot_at_scale():
    big = 64 << 20
    assert pm.allreduce_time_s(big, 8, "two_shot", SPEC) < \
        pm.allreduce_time_s(big, 8, "one_shot", SPEC)


def test_fused_estimates_bounded_by_parts():
    t = pm.ag_gemm_time_s(8192, 4096, 4096, 8, 2, SPEC)
    t_gemm = pm.gemm_time_s(8192, 4096, 4096, 2, SPEC)
    t_ag = pm.allgather_full_mesh_time_s(8192 * 4096 * 2, 8, SPEC)
    assert t >= max(t_gemm, t_ag)
    assert t <= t_gemm + 2 * t_ag  # overlap: never worse than serial + fill


def test_rank_gemm_tiles_prefers_large_aligned_tiles():
    cands = [(8, 128, 128), (256, 512, 512), (512, 512, 512), (64, 128, 256)]
    ranked = pm.rank_gemm_tiles(cands, 2048, 2048, 2048, 2, SPEC)
    # A degenerate (8, 128, 128) tiling must never rank first at this size.
    assert ranked[0] != (8, 128, 128)
    assert set(ranked) == set(cands)
    top2 = pm.rank_gemm_tiles(cands, 2048, 2048, 2048, 2, SPEC, top=2)
    assert len(top2) == 2 and top2 == ranked[:2]


def test_autotuner_pruning_keeps_measured_winner():
    """The model's top-8 must contain the config a measurement would pick —
    checked with a proxy cost (modeled time + noise-free eval) over the real
    candidate generator."""
    from triton_distributed_tpu.runtime.autotuner import gemm_tile_candidates

    m, n, k = 2048, 4096, 4096
    cands = gemm_tile_candidates(m, k, n, 2)
    ranked = pm.rank_gemm_tiles(cands, m, n, k, 2)
    full_best = ranked[0]
    pruned = pm.rank_gemm_tiles(cands, m, n, k, 2, top=8)
    assert full_best in pruned


def test_dcn_tier_much_slower_than_ici():
    nbytes = 16 << 20
    assert pm.dcn_collective_time_s(nbytes, 4, SPEC) > \
        pm.allgather_ring_time_s(nbytes, 4, SPEC)


def test_ranking_deterministic():
    cands = [(128, 256, 256), (256, 256, 256), (128, 512, 512)]
    r1 = pm.rank_gemm_tiles(cands, 1024, 1024, 1024, 2, SPEC)
    r2 = pm.rank_gemm_tiles(cands, 1024, 1024, 1024, 2, SPEC)
    assert r1 == r2


def test_p2p_and_a2a_models():
    assert pm.p2p_time_s(1 << 20, 1, SPEC) > 0
    assert pm.alltoall_time_s(1 << 20, 8, SPEC) > pm.alltoall_time_s(1 << 20, 2, SPEC)
    assert pm.alltoall_time_s(1 << 20, 1, SPEC) == 0.0


def test_crossover_is_spec_sensitive():
    """Sanity that the models actually consume the spec numbers."""
    fast = pm.ChipSpec("x", 459.0, 2765.0, 128 << 20, 1000.0, 6, 3, 25.0)
    slow = pm.ChipSpec("y", 459.0, 2765.0, 128 << 20, 1.0, 1, 1, 25.0)
    nb = 1 << 24
    assert pm.allgather_ring_time_s(nb, 8, fast) < \
        pm.allgather_ring_time_s(nb, 8, slow)


def test_gemm_small_batch_far_from_peak():
    """Decode GEMV-ish shapes (m=8) must model nowhere near peak: MXU
    quantization + HBM streaming of B dominate."""
    tf = pm.gemm_tflops(8, 4096, 4096, 2, SPEC)
    assert tf < 0.1 * SPEC.bf16_tflops
    # And the memory floor is respected: time >= weight-streaming time.
    t = pm.gemm_time_s(8, 4096, 4096, 2, SPEC)
    assert t >= (4096 * 4096 * 2) / (SPEC.hbm_gbps * 1e9)


def test_numpy_ints_accepted():
    t = pm.gemm_time_s(np.int64(512), np.int64(512), np.int64(512), 2, SPEC)
    assert t > 0
