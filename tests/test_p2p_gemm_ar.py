"""GEMM+AR and P2P transport tests (reference: test_gemm_ar, test_pp analogs)."""

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.ops import gemm_allreduce, p2p_shift
from triton_distributed_tpu.runtime.topology import detect_topology, ici_ring_order


def test_gemm_allreduce(ctx):
    n = ctx.num_ranks
    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.standard_normal((32, n * 16)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((n * 16, 128)) * 0.1, jnp.float32)
    got = gemm_allreduce(a, b, ctx, method="one_shot")
    np.testing.assert_allclose(np.asarray(got), np.asarray(a) @ np.asarray(b),
                               rtol=1e-4, atol=1e-5)


def test_p2p_shift(ctx):
    n = ctx.num_ranks
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)
    y = p2p_shift(x, ctx, shift=1)
    expected = np.roll(np.asarray(x).reshape(n, 8, 128), 1, axis=0).reshape(n * 8, 128)
    np.testing.assert_array_equal(np.asarray(y), expected)
    # pull direction
    y2 = p2p_shift(x, ctx, shift=-1)
    expected2 = np.roll(np.asarray(x).reshape(n, 8, 128), -1, axis=0).reshape(n * 8, 128)
    np.testing.assert_array_equal(np.asarray(y2), expected2)


def test_topology_cpu_mesh(ctx):
    topo = detect_topology()
    assert topo.num_devices == 8
    assert not topo.is_multi_host
    assert ici_ring_order(topo) is None  # no coords off-TPU: keep logical order


def test_gemm_ar_stream_matches_compose(ctx):
    """The fused chunk-overlapped stream kernel is value-identical to the
    sequential dot+AR compose and to the dense golden across repeated
    calls (parity flip), including a ragged row count that exercises the
    sublane padding."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.ops.gemm_allreduce import (
        gemm_ar_stream, gemm_ar_stream_workspace, gemm_allreduce,
    )
    from triton_distributed_tpu.runtime.context import shard_map_on

    rng = np.random.default_rng(7)
    n = 8
    for m in (16, 3):     # aligned + padded row counts
        a = jnp.asarray(rng.standard_normal((m, n * 64)) * 0.3, jnp.float32)
        b = jnp.asarray(rng.standard_normal((n * 64, 256)) * 0.3, jnp.float32)
        gold = np.asarray(a, np.float64) @ np.asarray(b, np.float64)

        def run(al, bl):
            ws, idx = gemm_ar_stream_workspace(n, al.shape[0], bl.shape[1],
                                               al.dtype)
            outs = []
            for _ in range(3):   # repeated steady-state calls, parity flip
                y, ws, idx = gemm_ar_stream(al, bl, ws, idx, axis="tp",
                                            num_ranks=n)
                outs.append(y)
            return jnp.stack(outs)

        outs = shard_map_on(ctx, run, (P(None, "tp"), P("tp")),
                            P(None))(a, b)
        compose = gemm_allreduce(a, b, ctx, method="one_shot")
        for t in range(3):
            np.testing.assert_allclose(np.asarray(outs)[t], gold,
                                       rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(compose), gold, rtol=2e-4,
                                   atol=2e-4)
