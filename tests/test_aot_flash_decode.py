"""AOT-compiled flash-decode with bucketed sequence dispatch — the
reference's production AOT use case (scripts/aot_kernels.txt compiles
gqa_fwd_batch_decode for a space of MAX_SEQ buckets; the C runtime picks
the smallest compiled bucket >= runtime length)."""

import numpy as np

import jax
import jax.numpy as jnp

from triton_distributed_tpu.ops.flash_decode import flash_decode_local
from triton_distributed_tpu.tools.aot import aot_compile_spaces


def _spec(b, s, hq, hkv, d):
    return (jax.ShapeDtypeStruct((b, hq, d), jnp.float32),
            jax.ShapeDtypeStruct((b, s, hkv, d), jnp.float32),
            jax.ShapeDtypeStruct((b, s, hkv, d), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.int32))


def test_aot_flash_decode_buckets(ctx):
    b, hq, hkv, d = 2, 8, 4, 32

    @aot_compile_spaces([
        {"args": _spec(b, 64, hq, hkv, d), "bucket": ((1, 1), (2, 1))},
        {"args": _spec(b, 256, hq, hkv, d), "bucket": ((1, 1), (2, 1))},
    ], name="flash_decode_aot")
    def decode(q, k, v, kv_len):
        # Single-shard decode (the per-rank kernel the reference AOTs).
        return flash_decode_local(q, k, v, kv_len, num_ranks=1)

    af = decode.build()
    assert af.registry.size() >= 2

    # Runtime length 100 → bucket 256 (smallest compiled >= 100).
    rng = np.random.default_rng(0)
    s_real = 100
    q = rng.standard_normal((b, hq, d)).astype(np.float32)
    k = rng.standard_normal((b, s_real, hkv, d)).astype(np.float32)
    v = rng.standard_normal((b, s_real, hkv, d)).astype(np.float32)

    entry = af.select_bucket(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             jnp.asarray(s_real, jnp.int32), bucket=((1, 1), (2, 1)))
    assert entry is not None and entry.args_spec[1].shape[1] == 256

    cap = entry.args_spec[1].shape[1]
    k_pad = np.zeros((b, cap, hkv, d), np.float32)
    v_pad = np.zeros((b, cap, hkv, d), np.float32)
    k_pad[:, :s_real], v_pad[:, :s_real] = k, v
    out = entry.compiled(jnp.asarray(q), jnp.asarray(k_pad),
                         jnp.asarray(v_pad), jnp.asarray(s_real, jnp.int32))

    # Golden: dense attention over the valid rows.
    groups = hq // hkv
    kk = np.repeat(k, groups, axis=2)
    vv = np.repeat(v, groups, axis=2)
    logits = np.einsum("bhd,bkhd->bhk", q, kk) / np.sqrt(d)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhk,bkhd->bhd", p, vv)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)