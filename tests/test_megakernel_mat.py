"""GEMM_MAT unit tests — the round-5 matrix-workspace GEMM path (static
per-spec specialized branches; tasks.py GEMM_MAT, builder.gemm_mat).

The decode-step tests exercise the fused model assembly; these cover the
task in isolation at edge shapes: multi-strip, pair (silu) epilogue,
residual epilogue, sub-512 K chunks, spec dedup, and validation errors.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from triton_distributed_tpu.megakernel.builder import MegaKernelBuilder
from triton_distributed_tpu.megakernel.tasks import (
    MAT_COLS, TILE, MatHandle, MatSpec, mat_chunk_rows,
)


def _run_one(k, n, pair=False, resid=False, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    mb = MegaKernelBuilder()
    a = mb.tensor(TILE, k)
    w = mb.tensor_mat(k, n, pair=pair)
    o = mb.tensor(TILE, n)
    r = mb.tensor(TILE, n) if resid else None
    mb.gemm_mat(o, a, w, residual=r)
    comp = mb.compile(dtype=dtype)
    av = rng.standard_normal((TILE, k)).astype(np.float32) * 0.1
    feeds = {a: av}
    if pair:
        g = rng.standard_normal((k, n)).astype(np.float32) * 0.1
        u = rng.standard_normal((k, n)).astype(np.float32) * 0.1
        feeds[w] = (g, u)
        gx = av @ g
        want = gx / (1 + np.exp(-gx)) * (av @ u)
    else:
        wv = rng.standard_normal((k, n)).astype(np.float32) * 0.1
        feeds[w] = wv
        want = av @ wv
    if resid:
        rv = rng.standard_normal((TILE, n)).astype(np.float32) * 0.1
        feeds[r] = rv
        want = want + rv
    (out,) = comp.run(feeds, outputs=[o])
    return np.asarray(out, np.float32), want


@pytest.mark.parametrize("k,n", [(256, 512), (512, 1024), (1024, 2048),
                                 (384, 256), (512, 1152)])
def test_plain_shapes(k, n):
    out, want = _run_one(k, n)
    np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-4)


def test_pair_silu_multi_strip():
    out, want = _run_one(512, 1536, pair=True)
    np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-4)


def test_residual_multi_strip():
    out, want = _run_one(512, 2048, resid=True)
    np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-4)


def test_bf16_workspace():
    out, want = _run_one(512, 1024, dtype=jnp.bfloat16)
    np.testing.assert_allclose(out, want, rtol=5e-2, atol=5e-2)


def test_spec_dedup_and_queue_words():
    mb = MegaKernelBuilder()
    a = mb.tensor(TILE, 512)
    w1 = mb.tensor_mat(512, 1024)
    w2 = mb.tensor_mat(512, 1024)
    w3 = mb.tensor_mat(512, 2048)
    o1, o2 = mb.tensor(TILE, 1024), mb.tensor(TILE, 1024)
    o3 = mb.tensor(TILE, 2048)
    mb.gemm_mat(o1, a, w1)
    mb.gemm_mat(o2, a, w2)     # same spec -> deduped
    mb.gemm_mat(o3, a, w3)     # new spec (ns/nt differ)
    comp = mb.compile()
    assert len(comp.mat_specs) == 2
    assert comp.mat_specs[0] == MatSpec(kt=4, ns=1, nt_out=8, kch=512,
                                        epi=0)


def test_mat_handle_geometry():
    h = MatHandle(0, 512, 1536, pair=False)
    assert h.n_strips == 2 and h.rows == 1024          # 1024 + pad strip
    hp = MatHandle(0, 512, 1536, pair=True)
    assert hp.n_strips == 3 and hp.rows == 1536        # 512-col halves
    assert mat_chunk_rows(4096) == 512
    assert mat_chunk_rows(1536) == 512
    assert mat_chunk_rows(256) == 256
    assert mat_chunk_rows(384) == 128


def test_validation_errors():
    mb = MegaKernelBuilder()
    a = mb.tensor(TILE, 512)
    w = mb.tensor_mat(512, 1024)
    o = mb.tensor(TILE, 1024)
    bad_r = mb.tensor(TILE, 512)
    with pytest.raises(ValueError, match="residual"):
        mb.gemm_mat(o, a, w, residual=bad_r)
    wp = mb.tensor_mat(512, 1024, pair=True)
    good_r = mb.tensor(TILE, 1024)
    with pytest.raises(ValueError, match="mutually exclusive"):
        mb.gemm_mat(o, a, wp, residual=good_r)
    with pytest.raises(ValueError, match="shape mismatch"):
        mb.gemm_mat(o, mb.tensor(TILE, 256), w)
    with pytest.raises(TypeError):
        mb.gemm(o, a, w)     # tile-path gemm rejects a MatHandle


def test_step_requires_wsm():
    mb = MegaKernelBuilder()
    a = mb.tensor(TILE, 256)
    w = mb.tensor_mat(256, 256)
    o = mb.tensor(TILE, 256)
    mb.gemm_mat(o, a, w)
    comp = mb.compile()
    ws = comp.make_workspace({a: np.zeros((TILE, 256), np.float32)})
    with pytest.raises(ValueError, match="wsm"):
        comp.step(ws)


def test_step_validates_wsm_shape_and_dtype():
    """A stale/undersized wsm (built for a different program) must fail
    loudly instead of DMAing weight rows from out-of-bounds indices."""
    mb = MegaKernelBuilder()
    a = mb.tensor(TILE, 256)
    w = mb.tensor_mat(256, 256)
    o = mb.tensor(TILE, 256)
    mb.gemm_mat(o, a, w)
    comp = mb.compile()
    ws = comp.make_workspace({a: np.zeros((TILE, 256), np.float32)})
    good = comp.make_workspace_mat({w: np.zeros((256, 256), np.float32)})
    with pytest.raises(ValueError, match="does not fit"):
        comp.step(ws, wsm=good[: comp.num_mrows - 1])       # undersized
    with pytest.raises(ValueError, match="does not fit"):
        comp.step(ws, wsm=good[:, : MAT_COLS // 2])         # wrong width
    with pytest.raises(ValueError, match="dtype"):
        comp.step(ws, wsm=good.astype(jnp.bfloat16))        # wrong dtype


def test_pad_strip_columns_are_inert():
    """A 1152-wide matrix pads its second strip to MAT_COLS; the pad
    columns must not leak into the stored output tiles."""
    out, want = _run_one(256, 1152, seed=3)
    np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-4)
    assert MAT_COLS == 1024
