"""comm-lint integration: the ops library is protocol-clean, and each of
the four invariant classes catches a deliberately seeded violation.

The seeded kernels below are written exactly like the real ops (kernel_call
+ shmem/dl primitives) but each carries one canonical protocol bug:

* wrong wait delta        -> delta-imbalance
* missing wait_send/quiet -> unawaited-dma
* circular signal/wait    -> deadlock
* SignalOp.SET            -> lint-set-signal (misuse lint)
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.analysis import check, trace_op
from triton_distributed_tpu.analysis.registry import analyze_op, build_registry
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import any_spec, kernel_call


def _kinds(report):
    return {v.kind for v in report.violations}


# ---------------------------------------------------------------------------
# The shipped ops library is protocol-clean.
# ---------------------------------------------------------------------------

# Cheap pure-protocol ops run at 2 and 4 ranks; the attention family runs
# real interpret-mode flash kernels per rank, so it is checked at 2 ranks
# here (the CLI sweep covers the full 2/4/8 matrix).
_FAST_OPS = ["allgather", "reduce_scatter", "allreduce", "all_to_all", "p2p",
             "allgather_gemm", "gemm_reduce_scatter", "gemm_allreduce",
             "multi_axis", "two_level"]
_HEAVY_OPS = ["flash_decode", "moe", "ulysses", "ring_attention",
              "sp_ag_attention"]


@pytest.mark.parametrize("op", _FAST_OPS)
def test_ops_library_protocol_clean(op):
    for report in analyze_op(op, ranks=(2, 4)):
        assert report.ok, (
            f"{report.op}: " + "; ".join(v.message for v in report.violations))
        assert report.n_kernels > 0, f"{report.op}: no kernels traced"


@pytest.mark.parametrize("op", _HEAVY_OPS)
def test_ops_library_protocol_clean_heavy(op):
    for report in analyze_op(op, ranks=(2,)):
        assert report.ok, (
            f"{report.op}: " + "; ".join(v.message for v in report.violations))
        assert report.n_events > 0


def test_registry_covers_issue_surface():
    names = set(build_registry())
    required = {"allgather", "reduce_scatter", "allreduce", "all_to_all",
                "p2p", "allgather_gemm", "gemm_reduce_scatter",
                "flash_decode", "moe", "ulysses", "two_level", "multi_axis",
                "ring_attention", "sp_ag_attention",
                "hierarchical", "hierarchical_sp"}
    assert required <= names


# ---------------------------------------------------------------------------
# Seeded violations — each invariant class must catch its bug.
# ---------------------------------------------------------------------------

def _run_seeded(kernel_builder, n=4):
    """Trace a seeded full-mesh kernel on an n-rank tp mesh."""

    def driver(dims):
        nn = dims["tp"]
        kernel = functools.partial(kernel_builder, nn, "tp")
        x = np.ones((16, 128), np.float32)
        kernel_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((nn * 16, 128), jnp.float32),
            in_specs=[any_spec()],
            out_specs=any_spec(),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((max(nn - 1, 1),)),
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.REGULAR,
            ],
            uses_barrier=True,
        )(x)

    return check(trace_op(driver, axes=("tp",), dims=(n,), name="seeded"))


def _push_all(n, axis, x_ref, out_ref, send_sems, recv_sem):
    """The correct full-mesh push half every seeded kernel starts from."""
    import jax.experimental.pallas as pl

    me = dl.rank(axis)
    my_slot = out_ref.at[pl.ds(me * x_ref.shape[0], x_ref.shape[0])]
    handles = []
    for i in range(n - 1):
        peer = jax.lax.rem(me + 1 + i, n)
        handles.append(shmem.putmem_nbi_block(x_ref, my_slot, send_sems.at[i],
                                              recv_sem, peer, axis))
    return handles


def test_seeded_wrong_wait_delta_caught():
    """Waiting n-2 deliveries out of n-1 leaves unconsumed recv bytes."""

    def kernel(n, axis, x_ref, out_ref, send_sems, recv_sem, flag):
        shmem.barrier_all(axis)
        handles = _push_all(n, axis, x_ref, out_ref, send_sems, recv_sem)
        shmem.quiet(*handles)
        shmem.wait_deliveries(x_ref, recv_sem, n - 2)   # BUG: should be n-1

    report = _run_seeded(kernel)
    assert "delta-imbalance" in _kinds(report), report.violations
    [v] = [v for v in report.violations if v.kind == "delta-imbalance"
           and v.rank == 0]
    assert "never consumed" in v.message


def test_seeded_overdrawn_wait_delta_caught():
    """Waiting n deliveries when only n-1 arrive is a hang: both the
    static delta check and the scheduler replay must flag it."""

    def kernel(n, axis, x_ref, out_ref, send_sems, recv_sem, flag):
        shmem.barrier_all(axis)
        handles = _push_all(n, axis, x_ref, out_ref, send_sems, recv_sem)
        shmem.quiet(*handles)
        shmem.wait_deliveries(x_ref, recv_sem, n)       # BUG: should be n-1

    report = _run_seeded(kernel)
    kinds = _kinds(report)
    assert "delta-imbalance" in kinds, report.violations
    assert "deadlock" in kinds, report.violations      # the machine wedges


def test_seeded_missing_wait_send_caught():
    """start() without quiet/wait_send: the fence obligation is unmet."""

    def kernel(n, axis, x_ref, out_ref, send_sems, recv_sem, flag):
        shmem.barrier_all(axis)
        _push_all(n, axis, x_ref, out_ref, send_sems, recv_sem)  # BUG: no quiet
        shmem.wait_deliveries(x_ref, recv_sem, n - 1)

    report = _run_seeded(kernel)
    assert "unawaited-dma" in _kinds(report), report.violations
    assert any("wait_send" in v.message for v in report.violations)


def test_seeded_signal_wait_cycle_caught():
    """Every rank waits for its LEFT neighbor's signal before signalling
    its RIGHT neighbor — a textbook cross-rank cycle."""

    def kernel(n, axis, x_ref, out_ref, send_sems, recv_sem, flag):
        me = dl.rank(axis)
        dl.wait(flag, 1)                                # BUG: wait first...
        dl.notify(flag, jax.lax.rem(me + 1, n))         # ...signal after

    report = _run_seeded(kernel)
    assert "deadlock" in _kinds(report), report.violations
    [v] = [v for v in report.violations if v.kind == "deadlock"
           and "cycle" in v.message]
    assert "->" in v.message


def test_seeded_set_signal_caught():
    """SignalOp.SET has no TPU lowering and must be linted."""

    def kernel(n, axis, x_ref, out_ref, send_sems, recv_sem, flag):
        me = dl.rank(axis)
        dl.notify(flag, jax.lax.rem(me + 1, n), op=dl.SignalOp.SET)  # BUG
        dl.wait(flag, 1)

    report = _run_seeded(kernel)
    assert "lint-set-signal" in _kinds(report), report.violations


def test_seeded_wait_never_signalled_caught():
    """A wait on a semaphore nobody signals is linted (and wedges)."""

    def kernel(n, axis, x_ref, out_ref, send_sems, recv_sem, flag):
        dl.wait(flag, 1)                                # BUG: nobody notifies

    report = _run_seeded(kernel)
    kinds = _kinds(report)
    assert "lint-unsignalled-wait" in kinds, report.violations
    assert "deadlock" in kinds    # starvation is also reported


def test_seeded_wrong_peer_axis_caught():
    """Signalling along an axis that is not in the mesh is a misuse lint."""

    def kernel(n, axis, x_ref, out_ref, send_sems, recv_sem, flag):
        me = dl.rank(axis)
        shmem.signal_op(flag, jax.lax.rem(me + 1, n), axis="not_an_axis")
        dl.wait(flag, 1)

    report = _run_seeded(kernel)
    assert "lint-bad-axis" in _kinds(report), report.violations


# ---------------------------------------------------------------------------
# SignalOp.SET is rejected by the real (un-shimmed) primitive too.
# ---------------------------------------------------------------------------

def test_signal_set_raises_outside_tracer():
    with pytest.raises(NotImplementedError):
        dl.notify(object(), 0, op=dl.SignalOp.SET)
    with pytest.raises(NotImplementedError):
        shmem.signal_op(object(), 0, op=dl.SignalOp.SET)


# ---------------------------------------------------------------------------
# Trace hygiene: the shims restore cleanly.
# ---------------------------------------------------------------------------

def test_instrumentation_uninstalls_cleanly():
    from triton_distributed_tpu.language import instrument

    before = instrument.originals()
    analyze_op("p2p", ranks=(2,))
    after = instrument.originals()
    changed = [k for k in before if before[k] is not after[k]]
    assert not changed, f"patch points left shimmed: {changed}"
