"""TP layers + dense model + engine tests on the 8-device CPU mesh.

Golden strategy (reference test_tp_mlp/test_tp_attn/test_e2e_inference,
SURVEY.md §4): the ``xla`` backend (plain lax collectives) is the golden;
the ``overlap``/``ar`` backends (Pallas kernels) must match it, and both
must match a single-device numpy-style forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.models import (
    Engine, init_dense_llm, init_kv_cache, tiny_config,
)
from triton_distributed_tpu.layers import (
    init_tp_mlp, tp_mlp_fwd, tp_mlp_specs,
)
from triton_distributed_tpu.runtime.context import shard_map_on
from jax.sharding import PartitionSpec as P

CFG = tiny_config()


def _ref_forward_logits(params, cfg, ids):
    """Single-device straight-line reference forward (last-token logits)."""
    from triton_distributed_tpu.models.dense import dense_prefill

    cache = init_kv_cache(cfg, ids.shape[0], max_seq=ids.shape[1],
                          dtype=jnp.float32)
    logits, cache = dense_prefill(params, cfg, ids, cache, num_ranks=1)
    return logits, cache


def test_tp_mlp_modes_agree(ctx):
    n, m, h, ffn = 8, 64, 128, 256
    rng = jax.random.key(0)
    params = init_tp_mlp(rng, h, ffn, jnp.float32)
    x = jax.random.normal(jax.random.key(1), (m, h), jnp.float32)

    golden = jax.nn.silu(x @ params["w_gate"]) * (x @ params["w_up"])
    golden = golden @ params["w_down"]

    specs = tp_mlp_specs("tp")
    # row-sharded modes
    for mode in ("overlap", "xla"):
        fn = shard_map_on(
            ctx,
            lambda p, xl: tp_mlp_fwd(p, xl, num_ranks=n, mode=mode),
            (specs, P("tp")), P("tp"))
        out = fn(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(golden),
                                   rtol=2e-4, atol=2e-4, err_msg=mode)
    # replicated modes
    for mode in ("ar", "xla_rep"):
        fn = shard_map_on(
            ctx,
            lambda p, xl: tp_mlp_fwd(p, xl, num_ranks=n, mode=mode),
            (specs, P()), P())
        out = fn(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(golden),
                                   rtol=2e-4, atol=2e-4, err_msg=mode)


GQA_CFG = tiny_config(num_heads=16)  # 2 q heads per kv head per device


@pytest.mark.parametrize("backend", ["xla", "overlap"])
@pytest.mark.parametrize("cfg", [CFG, GQA_CFG], ids=["mha", "gqa"])
def test_engine_prefill_matches_reference(ctx, backend, cfg):
    batch, seq = 2, 32
    params = init_dense_llm(jax.random.key(0), cfg)
    ids = jax.random.randint(jax.random.key(1), (batch, seq), 0,
                             cfg.vocab_size)

    ref_logits, _ = _ref_forward_logits(params, cfg, ids)

    eng = Engine(cfg, params, ctx, backend=backend, max_seq=64)
    logits, cache = eng.prefill(ids)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=5e-3, atol=5e-3)
    assert int(cache.offset) == seq


MOE_CFG = tiny_config(num_experts=16, num_experts_per_tok=2,
                      moe_intermediate_size=64)


@pytest.mark.parametrize("backend", ["xla", "overlap"])
def test_moe_engine_e2e(ctx, backend):
    """Qwen3-MoE-style model end-to-end: prefill + decode vs single-device
    reference (reference test_ep_moe_inference pattern)."""
    batch, seq, gen = 2, 16, 3
    params = init_dense_llm(jax.random.key(7), MOE_CFG)
    ids = jax.random.randint(jax.random.key(8), (batch, seq), 0,
                             MOE_CFG.vocab_size)

    eng = Engine(MOE_CFG, params, ctx, backend=backend, max_seq=64)
    toks = eng.serve(ids, gen)

    cur = np.asarray(ids)
    for step in range(gen):
        logits, _ = _ref_forward_logits(params, MOE_CFG, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits, axis=-1).astype(jnp.int32))
        np.testing.assert_array_equal(
            np.asarray(toks)[:, step], nxt,
            err_msg=f"moe backend={backend} divergence at step {step}")
        cur = np.concatenate([cur, nxt[:, None]], axis=1)


@pytest.mark.parametrize("backend", ["xla", "overlap"])
def test_engine_decode_matches_prefill(ctx, backend):
    """Tokens decoded step-by-step must equal re-running prefill over the
    extended prompt (KV-cache correctness)."""
    batch, seq, gen = 2, 16, 4
    params = init_dense_llm(jax.random.key(2), CFG)
    ids = jax.random.randint(jax.random.key(3), (batch, seq), 0,
                             CFG.vocab_size)

    eng = Engine(CFG, params, ctx, backend=backend, max_seq=64)
    toks = eng.serve(ids, gen)
    assert toks.shape == (batch, gen)

    # Golden: grow the prompt one token at a time through full prefills.
    cur = np.asarray(ids)
    for step in range(gen):
        logits, _ = _ref_forward_logits(params, CFG, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits, axis=-1).astype(jnp.int32))
        np.testing.assert_array_equal(
            np.asarray(toks)[:, step], nxt,
            err_msg=f"backend={backend} divergence at step {step}")
        cur = np.concatenate([cur, nxt[:, None]], axis=1)


def test_chunked_prefill_matches_full(ctx):
    """Bounded-memory chunked prefill (chunks attend the cached prefix via
    flash positional causality) is numerically equivalent to whole-prompt
    prefill: same last-token logits, same cache, same generation."""
    from triton_distributed_tpu.models.config import tiny_config
    from triton_distributed_tpu.models.dense import init_dense_llm
    from triton_distributed_tpu.models.engine import Engine

    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(3), cfg)
    ids = np.array([[5, 9, 23, 77, 41, 2, 8, 13]], np.int32)   # S=8

    eng = Engine(cfg, params, ctx, backend="auto", max_seq=32)
    logits_full, cache_full = eng.prefill(jnp.asarray(ids))
    logits_chunk, cache_chunk = eng.prefill(jnp.asarray(ids), chunk=4)

    np.testing.assert_allclose(np.asarray(logits_chunk),
                               np.asarray(logits_full), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(cache_chunk.k[:, :, :8]),
                               np.asarray(cache_full.k[:, :, :8]),
                               rtol=2e-4, atol=2e-4)
    assert int(cache_chunk.offset) == 8

    tok_f, cache_full = eng.decode(jnp.argmax(logits_full, -1).astype(
        jnp.int32), cache_full)
    tok_c, cache_chunk = eng.decode(jnp.argmax(logits_chunk, -1).astype(
        jnp.int32), cache_chunk)
    np.testing.assert_array_equal(np.asarray(tok_f), np.asarray(tok_c))


def test_decode_force_ar_kernel_runs_at_n1():
    """force_ar_kernel must actually route every layer reduction through
    the parity-stream kernel at n=1 (the bench's labeled with-AR number):
    the threaded call_index advances once per reduction site, and logits
    match the bare path."""
    import jax.random as jrandom

    from triton_distributed_tpu.models.config import tiny_config
    from triton_distributed_tpu.models.dense import (
        dense_decode_step, init_dense_llm,
    )
    from triton_distributed_tpu.models.kv_cache import init_kv_cache
    from triton_distributed_tpu.ops.allreduce import ar_stream_workspace

    cfg = tiny_config()
    params = init_dense_llm(jrandom.PRNGKey(0), cfg)
    cache = init_kv_cache(cfg, 1, 64)
    cache = cache._replace(offset=jnp.int32(3))
    tok = jnp.zeros((1,), jnp.int32)

    logits0, _ = dense_decode_step(params, cfg, tok, cache, num_ranks=1)

    from triton_distributed_tpu.runtime import initialize_distributed
    from triton_distributed_tpu.runtime.context import shard_map_on
    from jax.sharding import PartitionSpec as P

    ctx1 = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])

    def run(params, tok, cache):
        ws, idx = ar_stream_workspace(1, 1, cfg.hidden_size, jnp.float32)
        logits, _, (ws2, idx2) = dense_decode_step(
            params, cfg, tok, cache, num_ranks=1, ar_state=(ws, idx),
            force_ar_kernel=True)
        return logits, idx2

    logits1, idx2 = shard_map_on(ctx1, run, (P(), P(), P()),
                                 (P(), P()))(params, tok, cache)
    # one AR per attn out-proj + one per MLP down-proj, per layer
    assert int(idx2) == 2 * cfg.num_layers
    np.testing.assert_allclose(np.asarray(logits1), np.asarray(logits0),
                               rtol=1e-5, atol=1e-5)


def test_engine_fused_gemm_ar_matches_default(ctx, monkeypatch):
    """TDTPU_GEMM_AR=1 routes every decode reduction through the fused
    chunk-overlapped GEMM+AR stream kernel; greedy tokens must match the
    default dot + parity-AR path."""
    params = init_dense_llm(jax.random.PRNGKey(0), CFG)
    ids = np.array([[3, 141, 59, 26]], np.int32)

    eng = Engine(CFG, params, ctx, backend="ar", max_seq=64)
    base = np.asarray(eng.serve(jnp.asarray(ids), gen_len=6))

    monkeypatch.setenv("TDTPU_GEMM_AR", "1")
    eng2 = Engine(CFG, params, ctx, backend="ar", max_seq=64)
    assert eng2._use_fused_gemm_ar()
    fused = np.asarray(eng2.serve(jnp.asarray(ids), gen_len=6))
    np.testing.assert_array_equal(fused, base)
