"""SP/long-context attention tests: ring attention, SP AG attention,
distributed flash-decode — goldens vs full dense attention on the 8-CPU mesh.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops import (
    ring_attention,
    sp_ag_attention,
    flash_decode,
)


def _dense_attn(q, k, v, causal, kv_valid=None):
    """Full-precision reference GQA attention. q: (B,Sq,hq,d); k/v (B,Sk,hkv,d)."""
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    qf = q.astype(np.float64).reshape(b, sq, hkv, g, d)
    logits = np.einsum("bqhgd,bkhd->bqhgk", qf, k.astype(np.float64))
    logits /= math.sqrt(d)
    if causal:
        mask = np.tril(np.ones((sq, sk), bool))
        logits = np.where(mask[None, :, None, None, :], logits, -np.inf)
    if kv_valid is not None:
        valid = np.arange(sk) < kv_valid
        logits = np.where(valid[None, None, None, None, :], logits, -np.inf)
    logits -= logits.max(-1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(-1, keepdims=True)
    out = np.einsum("bqhgk,bkhd->bqhgd", p, v.astype(np.float64))
    return out.reshape(b, sq, hq, d)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hq,hkv", [(8, 8), (16, 8)], ids=["mha", "gqa"])
def test_ring_attention_golden(ctx, causal, hq, hkv):
    b, s, d, n = 2, 64, 32, 8
    rng = np.random.default_rng(0)
    q = rng.standard_normal((b, s, hq, d)).astype(np.float32)
    k = rng.standard_normal((b, s, hkv, d)).astype(np.float32)
    v = rng.standard_normal((b, s, hkv, d)).astype(np.float32)

    out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                         ctx, causal=causal)
    ref = _dense_attn(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_sp_ag_attention_golden(ctx, causal):
    b, s, hq, hkv, d, n = 1, 64, 16, 8, 32, 8
    rng = np.random.default_rng(1)
    q = rng.standard_normal((b, s, hq, d)).astype(np.float32)
    k = rng.standard_normal((b, s, hkv, d)).astype(np.float32)
    v = rng.standard_normal((b, s, hkv, d)).astype(np.float32)

    out = sp_ag_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          ctx, causal=causal)
    ref = _dense_attn(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("method", ["xla", "pallas"])
def test_flash_decode_golden(ctx, method):
    """Split-KV decode with ragged per-shard lengths vs dense reference."""
    b, hq, hkv, d, n, s_shard = 2, 16, 8, 32, 8, 16
    rng = np.random.default_rng(2)
    q = rng.standard_normal((b, hq, d)).astype(np.float32)
    k = rng.standard_normal((b, n * s_shard, hkv, d)).astype(np.float32)
    v = rng.standard_normal((b, n * s_shard, hkv, d)).astype(np.float32)
    # Ragged: shard r holds kv_lens[r] valid rows (shard 3 fully empty).
    kv_lens = np.asarray([16, 7, 12, 0, 16, 1, 9, 4], np.int32)

    out = flash_decode(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                       jnp.asarray(kv_lens), ctx, method=method)

    # Dense golden over the concatenation of valid rows only.
    rows = []
    for r in range(n):
        st = r * s_shard
        rows.append(np.arange(st, st + kv_lens[r]))
    sel = np.concatenate(rows)
    ref = _dense_attn(q[:, None], k[:, sel], v[:, sel], causal=False)[:, 0]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_golden(ctx, causal):
    """Head-exchange SP attention (Ulysses — beyond-reference addition)
    vs the dense golden; H divisible by the axis."""
    from triton_distributed_tpu.ops.ulysses import ulysses_attention

    b, s, hq, hkv, d, n = 1, 64, 16, 8, 32, 8
    rng = np.random.default_rng(7)
    q = rng.standard_normal((b, s, hq, d)).astype(np.float32)
    k = rng.standard_normal((b, s, hkv, d)).astype(np.float32)
    v = rng.standard_normal((b, s, hkv, d)).astype(np.float32)

    out = ulysses_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            ctx, causal=causal)
    ref = _dense_attn(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_flash_decode_ag_stream_repeated(ctx):
    """SP decode steady state: flash_decode through the barrier-free parity
    AG (ag_state threaded over repeated steps) matches the one-shot path."""
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.ops.allgather import ag_stream_workspace
    from triton_distributed_tpu.ops.flash_decode import flash_decode_local
    from triton_distributed_tpu.runtime import shard_map_on

    n, b, hq, hkv, d, s_shard = 8, 2, 4, 2, 64, 32
    rng = np.random.default_rng(11)
    q = rng.standard_normal((b, hq, d)).astype(np.float32)
    k = rng.standard_normal((n, b, s_shard, hkv, d)).astype(np.float32)
    v = rng.standard_normal((n, b, s_shard, hkv, d)).astype(np.float32)

    def run(ql, kl, vl):
        kl, vl = kl[0], vl[0]
        ws, idx = ag_stream_workspace(n, b * hq, d + 2, jnp.float32)
        outs = []
        for _ in range(3):
            out, (ws, idx) = flash_decode_local(
                ql, kl, vl, jnp.int32(s_shard), axis="tp", num_ranks=n,
                ag_state=(ws, idx))
            outs.append(out)
        ref = flash_decode_local(ql, kl, vl, jnp.int32(s_shard),
                                 axis="tp", num_ranks=n, method="xla")
        return jnp.stack(outs), ref

    fn = shard_map_on(ctx, run, (P(), P("tp"), P("tp")), (P(), P()))
    outs, ref = fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for t in range(3):
        np.testing.assert_allclose(np.asarray(outs)[t], np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
