"""Regression gate + bench history + SLO watchdog tests (ISSUE 4):
ledger round-trip, noise-aware band behavior (quiet within band, loud on
a seeded slip, direction-aware for latencies), the committed BENCH_r01-r05
trajectory passing, CLI exit codes, SLO violation span emission, and the
ledger-quote freshness contract."""

import json
import os
import subprocess
import sys

import pytest

from triton_distributed_tpu import obs
from triton_distributed_tpu.obs import gate
from triton_distributed_tpu.obs import history as hist
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.obs import slo
from triton_distributed_tpu.obs import trace as obs_trace

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_run():
    obs_trace.disable()
    yield
    obs_trace.disable()


def _rec(rnd, **metrics):
    return hist.Record(metrics=metrics, window=f"2026-07-{10 + rnd:02d} 12:00",
                       round=rnd, source=f"synthetic r{rnd}")


# ---------------------------------------------------------------------------
# History ledger.
# ---------------------------------------------------------------------------

def test_ledger_roundtrip(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    rec = _rec(1, vs_baseline=0.95, value=170.0)
    rec.gate = {"status": "ok", "verdicts": []}
    rec.fingerprint = {"jax": "0.4.37", "backend": "tpu"}
    hist.append(rec, path)
    hist.append(_rec(2, vs_baseline=0.96), path)
    back = hist.load_jsonl(path)
    assert [r.round for r in back] == [1, 2]
    assert back[0].gate == {"status": "ok", "verdicts": []}
    assert back[0].fingerprint["backend"] == "tpu"
    assert back[0].value("vs_baseline") == 0.95
    assert back[0].window == rec.window


def test_load_history_merges_driver_round_files(tmp_path):
    """A BENCH_rNN.json next to the ledger that the JSONL doesn't carry
    is auto-backfilled — ledger/driver drift is structurally impossible."""
    path = str(tmp_path / "hist.jsonl")
    hist.append(_rec(1, vs_baseline=0.9), path)
    with open(tmp_path / "BENCH_r02.json", "w") as f:
        json.dump({"n": 2, "rc": 0,
                   "tail": "WARNING:2026-08-01 10:00:00 ...\n{}",
                   "parsed": {"metric": "m", "unit": "TFLOP/s",
                              "value": 171.0, "vs_baseline": 0.93}}, f)
    recs = hist.load_history(path)
    assert [r.round for r in recs] == [1, 2]
    assert recs[1].value("value") == 171.0
    assert recs[1].window == "2026-08-01 10:00"
    assert recs[1].quarantined is None


def test_backfill_quarantines_elided_rounds(tmp_path):
    """The round-1 failure mode (clamped differential → 17 EFLOP/s) is
    kept in the ledger but excluded from gate trajectories."""
    with open(tmp_path / "BENCH_r01.json", "w") as f:
        json.dump({"n": 1, "rc": 0, "tail": "",
                   "parsed": {"unit": "TFLOP/s", "value": 17179869.184,
                              "vs_baseline": 1.0}}, f)
    rec = hist.parse_bench_round_file(str(tmp_path / "BENCH_r01.json"))
    assert rec.quarantined and "exceeds any real chip" in rec.quarantined
    assert hist.trajectory([rec], "value") == []
    assert hist.trajectory([rec], "value",
                           include_quarantined=True) == [17179869.184]


def test_unreliable_strings_stay_refused():
    r = _rec(1, decode_step_ms_with_ar_kernel="unreliable this window")
    assert r.value("decode_step_ms_with_ar_kernel") is None


def test_window_spread_rel():
    r = _rec(1, window_spread={
        "xla": {"p50_ms": 100.0, "p95_ms": 120.0, "min_ms": 100.0, "n": 8},
        "pinned": {"p50_ms": 100.0, "p95_ms": 110.0, "min_ms": 100.0,
                   "n": 8}})
    assert r.window_spread_rel() == pytest.approx(0.2)


# ---------------------------------------------------------------------------
# Gate bands.
# ---------------------------------------------------------------------------

def test_gate_quiet_within_band():
    priors = [_rec(1, vs_baseline=0.93), _rec(2, vs_baseline=0.95),
              _rec(3, vs_baseline=0.94)]
    cur = _rec(4, vs_baseline=0.945)
    report = gate.evaluate(cur, priors)
    assert report.status == "ok"
    v = {x.key: x for x in report.verdicts}["vs_baseline"]
    assert v.status == "ok" and v.n_priors == 3


def test_gate_fires_on_seeded_slip():
    priors = [_rec(1, vs_baseline=0.95), _rec(2, vs_baseline=0.96),
              _rec(3, vs_baseline=0.94)]
    report = gate.evaluate(_rec(4, vs_baseline=0.70), priors)
    assert report.status == "regression"
    assert [v.key for v in report.regressions] == ["vs_baseline"]


def test_gate_direction_aware_for_latencies():
    priors = [_rec(1, decode_step_ms_megakernel=6.4),
              _rec(2, decode_step_ms_megakernel=6.5)]
    up = gate.evaluate(_rec(3, decode_step_ms_megakernel=9.8), priors)
    assert up.status == "regression"
    down = gate.evaluate(_rec(3, decode_step_ms_megakernel=4.1), priors)
    assert down.status == "ok"
    v = {x.key: x for x in down.verdicts}["decode_step_ms_megakernel"]
    assert v.status == "improved"


def test_gate_band_widens_with_trajectory_noise():
    """A wild trajectory earns a wide band: the same absolute reading
    that fires against a tight history passes against a noisy one."""
    tight = [_rec(i, value=170.0 + i) for i in range(1, 4)]
    noisy = [_rec(1, value=120.0), _rec(2, value=170.0),
             _rec(3, value=210.0)]
    cur = _rec(4, value=140.0)
    assert gate.evaluate(cur, tight).status == "regression"
    assert gate.evaluate(cur, noisy).status == "ok"


def test_gate_insufficient_history_and_unreliable_pass():
    priors = [_rec(1, vs_baseline=0.95)]
    cur = _rec(2, vs_baseline=0.5,
               decode_step_ms_megakernel="unreliable this window")
    report = gate.evaluate(cur, priors)
    assert report.status == "ok"
    by = {v.key: v for v in report.verdicts}
    assert by["vs_baseline"].status == "insufficient-history"
    assert by["decode_step_ms_megakernel"].status == "unreliable"
    assert by["value"].status == "absent"


def test_gate_quarantined_priors_excluded():
    bad = _rec(1, value=17179869.0)
    bad.quarantined = "elided"
    priors = [bad, _rec(2, value=165.0), _rec(3, value=172.0)]
    report = gate.evaluate(_rec(4, value=168.0), priors)
    v = {x.key: x for x in report.verdicts}["value"]
    assert v.status == "ok" and v.n_priors == 2
    assert v.center == pytest.approx(168.5)


def test_gate_sustained_regression_keeps_firing():
    """A prior that was itself gated as a regression on a rung is
    excluded from the trajectory: the alarm record must not become the
    'worst recent prior' that vouches for the next equally-bad window."""
    priors = [_rec(1, vs_baseline=0.95), _rec(2, vs_baseline=0.96),
              _rec(3, vs_baseline=0.94)]
    slipped = _rec(4, vs_baseline=0.70)
    first = gate.evaluate(slipped, priors)
    assert first.status == "regression"
    # bench.py appends the slipped record WITH its verdict — replay that.
    slipped.gate = first.to_json()
    second = gate.evaluate(_rec(5, vs_baseline=0.70),
                           priors + [slipped])
    v = {x.key: x for x in second.verdicts}["vs_baseline"]
    assert second.status == "regression" and v.status == "regression"
    # A recovered window still gates clean against the healthy priors.
    recovered = gate.evaluate(_rec(5, vs_baseline=0.95),
                              priors + [slipped])
    assert recovered.status == "ok"


def test_gate_quarantined_current_does_not_gate_clean(capsys):
    """An elided/clamped current window (the round-1 1.7e7 TFLOP/s
    class) must not exit 0 — its numbers are not measurements."""
    cur = _rec(4, vs_baseline=0.96, value=17179869.0)
    cur.quarantined = "elided measurement"
    report = gate.evaluate(cur, [_rec(1, vs_baseline=0.95),
                                 _rec(2, vs_baseline=0.96)])
    assert report.status == "quarantined"
    assert report.note == "elided measurement"
    # CLI: gating the committed quarantined round 1 directly exits 2.
    rc = gate.main(["--current", os.path.join(_ROOT, "BENCH_r01.json")])
    assert rc == 2
    assert "QUARANTINED" in capsys.readouterr().out


def test_gate_real_trajectory_passes():
    """Acceptance: the committed BENCH_r01-r05 trajectory gates clean
    (r1 quarantined; the r4→r5 0.961→0.936 slip is within the noise
    band, not a regression)."""
    records = hist.load_history()
    rounds = [r for r in records if r.round is not None]
    assert len(rounds) >= 5
    assert any(r.quarantined for r in rounds if r.round == 1)
    report = gate.evaluate(rounds[-1], rounds[:-1])
    assert report.status == "ok", report.format_table()


def test_gate_cli_dryrun_real(capsys):
    assert gate.main(["--dryrun"]) == 0
    out = capsys.readouterr().out
    assert "gate: OK" in out and "dryrun copy" in out


def test_gate_cli_no_data_current_exits_2(tmp_path, capsys):
    """A current file carrying none of the gated rungs (empty/truncated/
    wrong-shaped JSON) must NOT read as a clean gate."""
    path = str(tmp_path / "hist.jsonl")
    for i, v in enumerate((0.95, 0.96, 0.94), start=1):
        hist.append(_rec(i, vs_baseline=v), path)
    cur = str(tmp_path / "current.json")
    with open(cur, "w") as f:
        json.dump({}, f)
    assert gate.main(["--history", path, "--current", cur]) == 2
    assert "NO-DATA" in capsys.readouterr().out


def test_gate_cli_driver_format_current_unwrapped(tmp_path, capsys):
    """A driver BENCH_rNN.json snapshot passed as --current gates the
    rungs under its 'parsed' key — a seeded slip in the wrapper format
    must exit 1, not pass vacuously with every rung absent."""
    path = str(tmp_path / "hist.jsonl")
    for i, v in enumerate((0.95, 0.96, 0.94), start=1):
        hist.append(_rec(i, vs_baseline=v), path)
    cur = str(tmp_path / "BENCH_r09.json")
    with open(cur, "w") as f:
        json.dump({"cmd": "python bench.py", "rc": 0, "tail": "",
                   "parsed": {"vs_baseline": 0.70}}, f)
    assert gate.main(["--history", path, "--current", cur]) == 1
    assert "REGRESSION" in capsys.readouterr().out


def test_gate_cli_current_already_in_ledger_not_its_own_prior(
        tmp_path, capsys):
    """A slipped live window that bench.py already appended (round-less)
    must not vouch for itself when re-gated via --current: the ledger
    copy is the same window, not trajectory evidence."""
    path = str(tmp_path / "hist.jsonl")
    for i, v in enumerate((0.95, 0.96, 0.94), start=1):
        hist.append(_rec(i, vs_baseline=v), path)
    slipped = hist.Record(metrics={"vs_baseline": 0.70},
                          window="2026-08-01 09:00", round=None,
                          source="bench.py",
                          gate={"status": "error", "error": "io"})
    hist.append(slipped, path)
    cur = str(tmp_path / "current.json")
    with open(cur, "w") as f:
        json.dump({"vs_baseline": 0.70}, f)   # the same window, re-gated
    assert gate.main(["--history", path, "--current", cur]) == 1
    assert "REGRESSION" in capsys.readouterr().out


def test_gate_cli_seeded_regression_exits_nonzero(tmp_path, capsys):
    path = str(tmp_path / "hist.jsonl")
    for i, v in enumerate((0.95, 0.96, 0.94), start=1):
        hist.append(_rec(i, vs_baseline=v), path)
    cur = str(tmp_path / "current.json")
    with open(cur, "w") as f:
        json.dump({"vs_baseline": 0.70}, f)
    out_json = str(tmp_path / "verdict.json")
    rc = gate.main(["--history", path, "--current", cur,
                    "--json", out_json])
    assert rc == 1
    assert "REGRESSION" in capsys.readouterr().out
    with open(out_json) as f:
        verdict = json.load(f)
    assert verdict["status"] == "regression"
    keys = [v["key"] for v in verdict["verdicts"]
            if v["status"] == "regression"]
    assert keys == ["vs_baseline"]


def test_gate_cli_dryrun_fails_on_regressed_committed_round(tmp_path):
    """--dryrun copies the newest round but gates it against the rounds
    BEFORE it — a regressed round committed to the history makes the CI
    step fail instead of trivially passing against itself."""
    path = str(tmp_path / "hist.jsonl")
    for i, v in enumerate((0.95, 0.96, 0.94, 0.70), start=1):
        hist.append(_rec(i, vs_baseline=v), path)
    assert gate.main(["--dryrun", "--history", path]) == 1


def test_gate_cli_latest_vs_priors(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    for i, v in enumerate((0.95, 0.96, 0.94, 0.95), start=1):
        hist.append(_rec(i, vs_baseline=v), path)
    assert gate.main(["--history", path]) == 0


# ---------------------------------------------------------------------------
# SLO watchdog.
# ---------------------------------------------------------------------------

def test_slo_observed_without_thresholds():
    reg = obs_metrics.Registry()
    reg.gauge("tdtpu_serve_tokens_per_s").set(42.0)
    section = slo.evaluate(slo.observed_from_registry(reg),
                           slo.SLOConfig())
    assert section["violations"] == 0
    by = {r["rule"]: r for r in section["rules"]}
    assert by["tokens_per_s_floor"]["status"] == "observed"
    assert by["tokens_per_s_floor"]["observed"] == 42.0
    assert by["step_latency_p99_ceiling"]["status"] == "no-data"


def test_slo_violation_emits_span_and_counters(tmp_path):
    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir)
    reg = obs_metrics.registry()
    reg.gauge("tdtpu_serve_tokens_per_s").set(5.0)
    for ms in (1.0, 2.0, 50.0):
        reg.histogram("tdtpu_decode_step_latency_ms").observe(ms)
    section = slo.check_serving(
        reg, cfg=slo.SLOConfig(tokens_per_s_min=10.0,
                               step_p99_ms_max=20.0))
    assert section["violations"] == 2
    assert reg.get("tdtpu_slo_violations_total").value == 2
    assert reg.get(
        "tdtpu_slo_violation_tokens_per_s_floor_total").value == 1
    obs.finish_run()
    with open(tmp_path / "run" / "host.spans.json") as f:
        events = json.load(f)["traceEvents"]
    viol = [e for e in events if e.get("name") == "slo.violation"]
    assert {e["args"]["rule"] for e in viol} == {
        "tokens_per_s_floor", "step_latency_p99_ceiling"}


def test_finish_run_embeds_slo_section(tmp_path, monkeypatch):
    monkeypatch.setenv("TDTPU_SLO_TOKENS_S_MIN", "10")
    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir)
    obs_metrics.registry().gauge("tdtpu_serve_tokens_per_s").set(5.0)
    obs.finish_run()
    with open(tmp_path / "run" / "metrics.json") as f:
        snap = json.load(f)
    assert snap["slo"]["violations"] == 1
    by = {r["rule"]: r for r in snap["slo"]["rules"]}
    assert by["tokens_per_s_floor"]["status"] == "violation"
    assert by["tokens_per_s_floor"]["threshold"] == 10.0


def test_slo_env_typo_degrades_to_observed(monkeypatch):
    """A malformed threshold must never crash the serve it watches —
    it warns and the rule degrades to observed-only."""
    monkeypatch.setenv("TDTPU_SLO_TOKENS_S_MIN", "5k")
    with pytest.warns(RuntimeWarning, match="not a number"):
        cfg = slo.SLOConfig.from_env()
    assert cfg.tokens_per_s_min is None
    reg = obs_metrics.Registry()
    reg.gauge("tdtpu_serve_tokens_per_s").set(1.0)
    section = slo.evaluate(slo.observed_from_registry(reg), cfg)
    assert section["violations"] == 0


def test_stall_fraction_from_summaries():
    assert slo.stall_fraction_from_summaries([]) is None
    s = [{"task_sum_s": 0.006, "measured_step_s": 0.010},
         {"task_sum_s": 0.009, "measured_step_s": 0.010}]
    assert slo.stall_fraction_from_summaries(s) == pytest.approx(0.4)


def test_live_stall_fraction_uses_newest_profile(tmp_path):
    """The live watchdog judges the serve that just happened: once a
    clean profile lands, an old stalled window must stop violating."""
    run = tmp_path / "run"
    run.mkdir()

    def profile(name, task_s, step_s, mtime):
        p = run / f"{name}.kernel_profile.json"
        with open(p, "w") as f:
            json.dump({"summary": {"task_sum_s": task_s,
                                   "measured_step_s": step_s}}, f)
        os.utime(p, (mtime, mtime))

    profile("stalled", 0.005, 0.010, 1000.0)   # stall fraction 0.5
    profile("clean", 0.0098, 0.010, 2000.0)    # stall fraction 0.02
    obs_d = slo.observed_from_registry(obs_metrics.Registry(),
                                       run_dir=str(run))
    assert obs_d["stall_fraction_ceiling"] == pytest.approx(0.02)


def test_report_check_fails_on_slo_violation(tmp_path):
    from triton_distributed_tpu.obs.report import main as report_main

    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir)
    reg = obs_metrics.registry()
    reg.counter("tdtpu_tokens_generated_total").inc(3)
    reg.histogram("tdtpu_decode_step_latency_ms").observe(1.5)
    reg.gauge("tdtpu_serve_tokens_per_s").set(5.0)
    obs.finish_run()
    # Overwrite the snapshot with a violating slo section (the watchdog
    # would have produced the same shape under a TDTPU_SLO_* env).
    with open(tmp_path / "run" / "metrics.json") as f:
        snap = json.load(f)
    snap["slo"] = slo.evaluate(
        slo.observed_from_snapshot(snap),
        slo.SLOConfig(tokens_per_s_min=10.0))
    assert snap["slo"]["violations"] == 1
    with open(tmp_path / "run" / "metrics.json", "w") as f:
        json.dump(snap, f)
    assert report_main([run_dir, "--check"]) == 1
    assert report_main([run_dir, "--check",
                        "--allow-slo-violations"]) == 0


def test_report_synthesizes_slo_for_legacy_runs(tmp_path, capsys):
    """A run dir written before the watchdog (no slo section) still gets
    one synthesized from the saved series."""
    from triton_distributed_tpu.obs.report import main as report_main

    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir)
    reg = obs_metrics.registry()
    reg.counter("tdtpu_tokens_generated_total").inc(1)
    reg.histogram("tdtpu_decode_step_latency_ms").observe(2.0)
    obs.finish_run()
    with open(tmp_path / "run" / "metrics.json") as f:
        snap = json.load(f)
    snap.pop("slo")
    with open(tmp_path / "run" / "metrics.json", "w") as f:
        json.dump(snap, f)
    assert report_main([run_dir, "--check"]) == 0
    assert "slo (0 violation(s))" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# Ledger quotes (the doc-drift guard) — the same check CI runs.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_measurements_and_ledger_quotes_fresh():
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "scripts",
                                      "gen_measurements.py"), "--check"],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
