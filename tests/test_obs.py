"""Observability layer tests (ISSUE 3): span tracer (incl. the
zero-overhead disabled fast path), metrics registry, megakernel
profile=True per-task timelines, replay-event JSONL lanes, report merge,
and the instrumented Engine leaving a complete run directory."""

import json
import time

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu import obs
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.obs import trace as obs_trace


@pytest.fixture(autouse=True)
def _no_leaked_run():
    """Every test starts and ends with the tracer disabled."""
    obs_trace.disable()
    yield
    obs_trace.disable()


# ---------------------------------------------------------------------------
# Tracer.
# ---------------------------------------------------------------------------

def test_disabled_span_is_shared_noop_singleton():
    s1 = obs_trace.span("anything", key=1)
    s2 = obs_trace.span("else")
    assert s1 is s2              # no allocation on the disabled path
    assert not obs_trace.is_enabled()
    obs_trace.instant("x")       # no-ops, no error
    obs_trace.counter("y", 1.0)


def test_disabled_span_overhead_is_negligible():
    """The acceptance criterion's testable form: with the tracer off, the
    instrumented pattern (`with span(...)`) costs single-digit
    microseconds per call at most — decode-step timing is unchanged
    within noise. Bound is deliberately loose (CI machines swing) yet far
    below any real decode step."""
    n = 50_000
    t0 = time.perf_counter()
    for _ in range(n):
        with obs_trace.span("decode_step"):
            pass
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 20e-6, f"disabled span costs {per_call * 1e6:.2f} us"


def test_spans_nest_and_export_chrome(tmp_path):
    t = obs_trace.enable(str(tmp_path))
    with obs_trace.span("outer", a=1):
        with obs_trace.span("inner"):
            time.sleep(0.002)
    obs_trace.instant("marker")
    obs_trace.counter("queue_depth", 3)
    obs_trace.disable()
    path = t.save()
    with open(path) as f:
        data = json.load(f)
    evs = {e["name"]: e for e in data["traceEvents"]}
    assert "outer" in evs and "inner" in evs and "marker" in evs
    outer, inner = evs["outer"], evs["inner"]
    # Complete events: inner nests inside outer on the same lane. ts is
    # rebased to unix-epoch us (~1.7e15), where float64 granularity is
    # ~0.25 us — allow 1 us of rounding slack.
    assert inner["ts"] >= outer["ts"] - 1.0
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0
    assert inner["dur"] >= 2_000 * 0.9   # >= ~2 ms in us
    assert outer["args"]["a"] == 1
    # Valid chrome trace per the report's validator.
    from triton_distributed_tpu.obs.report import validate_chrome

    assert validate_chrome(data) == []


def test_span_records_error_and_reraises(tmp_path):
    t = obs_trace.enable(str(tmp_path))
    with pytest.raises(ValueError):
        with obs_trace.span("boom"):
            raise ValueError("x")
    obs_trace.disable()
    ev = [e for e in t.events() if e["name"] == "boom"][0]
    assert ev["args"]["error"] == "ValueError"


# ---------------------------------------------------------------------------
# Metrics.
# ---------------------------------------------------------------------------

def test_metrics_counter_gauge_histogram():
    reg = obs_metrics.Registry()
    c = reg.counter("tok_total", "tokens")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("tps", "tokens/s")
    g.set(12.5)
    assert g.value == 12.5
    h = reg.histogram("lat_ms", "latency")
    for v in (1.0, 2.0, 3.0, 100.0):
        h.observe(v)
    assert h.count == 4
    assert h.quantile(50) == 2.0
    snap = reg.snapshot()
    assert snap["tok_total"]["value"] == 5
    assert snap["lat_ms"]["p50"] == 2.0
    assert snap["lat_ms"]["count"] == 4
    # Bucket counts (incl. the +Inf overflow bucket) must sum to count.
    assert sum(snap["lat_ms"]["buckets"].values()) == 4
    h_over = reg.histogram("over_ms", buckets=(1.0, 10.0))
    h_over.observe(2000.0)
    over = reg.snapshot()["over_ms"]
    assert over["buckets"]["+Inf"] == 1
    assert sum(over["buckets"].values()) == over["count"] == 1
    # Same name returns the same series; wrong kind raises.
    assert reg.counter("tok_total") is c
    with pytest.raises(TypeError):
        reg.gauge("tok_total")


def test_metrics_prometheus_exposition():
    reg = obs_metrics.Registry()
    reg.counter("a_total", "help a").inc(3)
    h = reg.histogram("b_ms", "help b", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0)
    h.observe(50.0)
    text = reg.to_prometheus()
    assert "# TYPE a_total counter" in text
    assert "a_total 3" in text
    assert "# TYPE b_ms histogram" in text
    assert 'b_ms_bucket{le="1.0"} 1' in text
    assert 'b_ms_bucket{le="10.0"} 2' in text
    assert 'b_ms_bucket{le="+Inf"} 3' in text
    assert "b_ms_count 3" in text


def test_metrics_save(tmp_path):
    reg = obs_metrics.Registry()
    reg.counter("x_total").inc()
    path = reg.save(str(tmp_path))
    with open(path) as f:
        assert json.load(f)["x_total"]["value"] == 1
    assert (tmp_path / "metrics.prom").exists()


# ---------------------------------------------------------------------------
# Megakernel profile=True. ONE shared program + ONE profiled step feed
# every test below (interpret-mode compiles dominate tier-1 wall time).
# ---------------------------------------------------------------------------

def _synthetic_prof():
    """A valid profile dump built by hand (the stamp format is
    [exec_index, type, out, a0, b0, k_tiles, a_stride, b_stride, arg, c0,
    d0] in lanes 0..10, -1 elsewhere) — lets the decode/render tests run
    without paying an interpret-mode kernel step; the slow-marked test
    below proves the kernel stamps exactly this."""
    from triton_distributed_tpu.megakernel.tasks import TaskType

    prof = np.full((2, 128), -1, np.int32)
    #          seq  type                        out a0 b0 kt as bs arg c0 d0
    prof[0, :11] = [0, int(TaskType.GEMM_WIDE), 3, 0, 1, 1, 1, 2, 2, 0, 0]
    prof[1, :11] = [1, int(TaskType.ADD), 5, 3, 3, 2, 0, 0, 0, 0, 0]
    return prof


@pytest.mark.slow
def test_megakernel_profile_step_stamp_and_parity():
    """The REAL kernel (interpret mode): profile=True stamps each grid
    step's queue row into its dump row, and does not perturb the
    computation (checked vs the analytic golden 2 * (x @ w))."""
    from triton_distributed_tpu.megakernel import MegaKernelBuilder
    from triton_distributed_tpu.obs.kernel_profile import decode_records

    mb = MegaKernelBuilder()
    m, h, f = 128, 128, 256
    x = mb.tensor(m, h)
    w = mb.tensor(h, f)
    gate = mb.tensor(m, f)
    act = mb.tensor(m, f)
    mb.gemm(gate, x, w)
    mb.add(act, gate, gate)
    comp = mb.compile()
    rng = np.random.default_rng(0)
    feeds = {t: rng.standard_normal((t.rows, t.cols)).astype(np.float32)
             * 0.1 for t in (x, w)}
    ws = comp.make_workspace({k: jnp.asarray(v) for k, v in feeds.items()})
    ws_p, prof = comp.step(ws, profile=True)
    prof = np.asarray(prof)
    assert prof.shape == (comp.num_exec, 128)
    recs = decode_records(prof)
    queue = np.asarray(comp.queue)
    assert [r.seq for r in recs] == list(range(comp.num_exec))
    for r in recs:   # the stamp IS the queue row
        assert r.type == int(queue[r.seq, 0])
        assert r.words["out"] == int(queue[r.seq, 1])
        assert r.words["k_tiles"] == int(queue[r.seq, 4])
    np.testing.assert_allclose(
        np.asarray(comp.gather_output(ws_p, act)),
        2.0 * (feeds[x] @ feeds[w]), rtol=1e-4, atol=1e-5)


def test_kernel_profile_decode_and_summary():
    from triton_distributed_tpu.obs.kernel_profile import (
        KernelProfile, decode_records,
    )

    prof = _synthetic_prof()
    recs = decode_records(prof)
    assert [r.type_name for r in recs] == ["GEMM_WIDE", "ADD"]
    assert recs[0].words == {"out": 3, "a0": 0, "b0": 1, "k_tiles": 1,
                             "a_stride": 1, "b_stride": 2, "arg": 2,
                             "c0": 0, "d0": 0}
    kp = KernelProfile.from_dump(prof, itemsize=4)
    summary = kp.summary()
    assert summary["n_tasks"] == 2
    assert set(summary["classes"]) == {"gemm", "elementwise"}
    assert summary["task_sum_s"] > 0


def test_kernel_profile_chrome_lanes_and_roundtrip(tmp_path):
    from triton_distributed_tpu.obs.kernel_profile import (
        KernelProfile, load_profile,
    )
    from triton_distributed_tpu.obs.report import validate_chrome

    kp = KernelProfile.from_dump(_synthetic_prof(), itemsize=4,
                                 measured_step_s=1.0, label="t")
    evs = kp.to_chrome_events()
    assert validate_chrome({"traceEvents": evs}) == []
    lanes = {e["args"]["name"] for e in evs
             if e.get("name") == "thread_name"}
    assert "gemm" in lanes and "elementwise" in lanes
    # measured_step_s >> task sum: the gap renders as a stall slice.
    assert any(e["name"] == "unattributed/stall" for e in evs)
    path = kp.save(str(tmp_path))
    kp2 = load_profile(path)
    assert kp2.summary() == kp.summary()


def test_measured_durations_override_estimates():
    from triton_distributed_tpu.obs.kernel_profile import (
        KernelProfile,
    )

    kp = KernelProfile.from_dump(_synthetic_prof(), itemsize=4,
                                 measured={"GEMM_WIDE": 42e-6})
    gemm = [r for r in kp.records if r.type_name == "GEMM_WIDE"]
    assert gemm and all(r.duration_kind == "measured"
                        and r.duration_s == 42e-6 for r in gemm)
    other = [r for r in kp.records if r.type_name != "GEMM_WIDE"]
    assert all(r.duration_kind == "estimated" for r in other)


# ---------------------------------------------------------------------------
# Replay-event JSONL + report lanes.
# ---------------------------------------------------------------------------

def test_traceset_jsonl_and_commlint_lanes(tmp_path):
    from triton_distributed_tpu.analysis.registry import build_registry
    from triton_distributed_tpu.analysis.tracer import trace_op
    from triton_distributed_tpu.obs.report import (
        commlint_lanes, commlint_metrics, validate_chrome,
    )

    drv = build_registry((2,))["allgather"]
    axes, dims = drv.meshes[0]
    ts = trace_op(drv.run, axes=axes, dims=dims, name="allgather@2")
    path = str(tmp_path / "allgather.events.jsonl")
    n = ts.to_jsonl(path)
    assert n == sum(len(r) for r in ts.events)
    with open(path) as f:
        first = json.loads(f.readline())
    assert first["kind"] == "trace_header"
    assert first["op"] == "allgather@2"
    assert first["dims"] == [2]

    evs = commlint_lanes(path, pid_base=95_000)
    assert validate_chrome({"traceEvents": evs}) == []
    pids = {e["pid"] for e in evs}
    assert pids == {95_000, 95_001}          # one pid per rank
    track_names = {e["args"]["name"] for e in evs
                   if e.get("name") == "thread_name"}
    assert any("sem" in t or "barrier" in t for t in track_names)

    m = commlint_metrics(str(tmp_path))
    assert m["tdtpu_commlint_dma_bytes_total"] > 0
    assert m["tdtpu_commlint_semaphore_waits_total"] > 0


def test_commlint_cli_events_dir(tmp_path):
    from triton_distributed_tpu.analysis.commlint import main as cl_main

    rc = cl_main(["--op", "allgather", "--ranks", "2",
                  "--events-dir", str(tmp_path / "ev")])
    assert rc == 0
    files = list((tmp_path / "ev").glob("*.events.jsonl"))
    assert len(files) == 1 and files[0].name == "allgather@2.events.jsonl"


# ---------------------------------------------------------------------------
# Instrumented Engine + report end-to-end.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_engine_serve_leaves_run_artifacts(ctx, tmp_path):
    """Slow tier: the CI observability smoke exercises the same path
    end-to-end (obs.report --dryrun serves a traced Engine and --check
    asserts the metrics series); tier-1 keeps only sub-second obs tests —
    the suite rides the edge of its 870 s budget."""
    import jax

    from triton_distributed_tpu.models import (
        Engine, init_dense_llm, tiny_config,
    )

    cfg = tiny_config()
    params = init_dense_llm(jax.random.key(0), cfg)
    eng = Engine(cfg, params, ctx, backend="xla", max_seq=32)
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab_size)

    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir)
    try:
        toks = eng.serve(ids, gen_len=3)
    finally:
        assert obs.finish_run() == run_dir
    assert toks.shape == (2, 3)

    with open(tmp_path / "run" / "metrics.json") as f:
        snap = json.load(f)
    # Token counter equals what serve() returned: batch 2 x gen_len 3
    # (2 decode steps + the prefill-sampled first token). The FIRST
    # prefill and first decode call compile — their wall times are routed
    # to the jit-compile series so the serving percentiles stay honest.
    assert snap["tdtpu_tokens_generated_total"]["value"] == 6
    assert snap["tdtpu_prefill_tokens_total"]["value"] == 16
    assert snap["tdtpu_decode_step_latency_ms"]["count"] == 1
    assert snap["tdtpu_jit_compile_ms"]["count"] == 2
    assert snap["tdtpu_serve_tokens_per_s"]["value"] > 0
    with open(tmp_path / "run" / "host.spans.json") as f:
        names = {e.get("name") for e in json.load(f)["traceEvents"]}
    assert {"engine.serve", "engine.prefill", "engine.decode_step",
            "jit_compile"} <= names


def test_report_merges_run_dir(tmp_path):
    """report.main on a run dir containing all three obs tiers exits 0
    with --check and writes a Perfetto-valid merged trace."""
    from triton_distributed_tpu.analysis.registry import build_registry
    from triton_distributed_tpu.analysis.tracer import trace_op
    from triton_distributed_tpu.obs.kernel_profile import KernelProfile
    from triton_distributed_tpu.obs.report import main as report_main
    from triton_distributed_tpu.obs.report import validate_chrome

    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir)
    with obs_trace.span("unit_span"):
        pass
    obs_metrics.registry().counter("tdtpu_tokens_generated_total").inc(3)
    obs_metrics.registry().histogram(
        "tdtpu_decode_step_latency_ms").observe(1.5)
    obs.finish_run()

    drv = build_registry((2,))["allreduce"]
    axes, dims = drv.meshes[0]
    trace_op(drv.run, axes=axes, dims=dims, name="allreduce@2").to_jsonl(
        f"{run_dir}/allreduce.events.jsonl")

    KernelProfile.from_dump(_synthetic_prof(), itemsize=4).save(run_dir)

    rc = report_main([run_dir, "--check",
                      "--require-lanes", "host,commlint,kernel"])
    assert rc == 0
    with open(f"{run_dir}/merged.trace.json") as f:
        merged = json.load(f)
    assert validate_chrome(merged) == []
    names = {e.get("name") for e in merged["traceEvents"]}
    assert "unit_span" in names
    # Missing-series check fails loudly.
    rc = report_main([run_dir, "--check",
                      "--require-series", "definitely_not_a_series"])
    assert rc == 1
