"""Step-phase profiler (ISSUE 18, docs/observability.md "Step
profiling & host bubble").

The load-bearing contracts: the telescoping phase stack makes the
PARTITION INVARIANT (Σ phases == iteration wall) hold by construction
for every iteration shape the serving stack produces — plain decode,
chunked-prefill mixed, preemption, evacuation preflight, spec-decode
verify, disagg migration advance, and fleet-router per-replica — all
byte-deterministic under the loop's injected clock; phase vectors ride
the flight ring with cumulative host/device counters; the bubble gauge
and per-phase histograms land in the registry (the fleet router merges
per-replica bubbles); and ``obs.report --check`` gates the lane.
"""

import json
import os
import warnings

import pytest

import jax

from triton_distributed_tpu import obs
from triton_distributed_tpu.models.config import tiny_config
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.obs import flight as obs_flight
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.obs import postmortem as obs_postmortem
from triton_distributed_tpu.obs import report as obs_report
from triton_distributed_tpu.obs import stepprof
from triton_distributed_tpu.obs import trace as obs_trace
from triton_distributed_tpu.obs.stepprof import StepProfiler
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving.loadgen import (
    LoadSpec, build_trace, run_trace,
)
from triton_distributed_tpu.serving.loop import ServingEngine


@pytest.fixture(autouse=True)
def _no_leaked_profiler():
    stepprof.disable()
    obs_trace.disable()
    yield
    stepprof.disable()
    obs_trace.disable()


@pytest.fixture(scope="module")
def ctx1():
    return initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])


@pytest.fixture(scope="module")
def served(ctx1):
    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(7), cfg)
    return Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                  page_size=4)


class CounterClock:
    """Deterministic injectable clock: monotone, no wall time."""

    def __init__(self, step: float = 0.001):
        self.t = 0.0
        self.step = step

    def __call__(self) -> float:
        self.t += self.step
        return round(self.t, 6)


def _assert_partition(recs):
    assert recs, "no phase records produced"
    for rec in recs:
        problem = stepprof.check_partition(rec)
        assert problem is None, problem


def _profiled_run(eng, trace, **kw):
    """One serving replay under a private profiler + CounterClock;
    returns (records, report)."""
    prof = StepProfiler()
    prev = stepprof.set_profiler(prof)
    try:
        se = ServingEngine(eng, clock=CounterClock(), **kw)
        report = run_trace(se, [dict(t) for t in trace])
    finally:
        stepprof.set_profiler(prev)
    return prof.records(), report


# ---------------------------------------------------------------------------
# The telescoping stack (unit level).
# ---------------------------------------------------------------------------

def test_telescoping_stack_partitions_with_nesting():
    """Nested phases (megakernel retarget inside decode_dispatch)
    telescope: each segment lands in exactly one phase, the parent
    keeps only its un-nested remainder, and Σ phases == wall."""
    sp = StepProfiler()
    sp.begin_iteration(0, 10.0)
    sp.enter("admit", 10.1)          # [10.0, 10.1] -> other
    sp.exit(10.3)                    # [10.1, 10.3] -> admit
    sp.enter("decode_dispatch", 10.3)
    sp.enter("retarget", 10.4)       # [10.3, 10.4] -> decode_dispatch
    sp.exit(10.6)                    # [10.4, 10.6] -> retarget
    sp.exit(10.7)                    # [10.6, 10.7] -> decode_dispatch
    rec = sp.finish_iteration(11.0)  # [10.7, 11.0] -> other
    assert rec["phases"] == {
        "admit": 200.0, "decode_dispatch": 200.0, "retarget": 200.0,
        "other": 400.0}
    assert rec["wall_ms"] == 1000.0
    assert rec["host_ms"] == 1000.0 and rec["device_ms"] == 0.0
    assert rec["host_bubble_frac"] == 1.0
    assert stepprof.check_partition(rec) is None


def test_dangling_phases_and_aborted_iterations_stay_partitioned():
    """An exception can skip exits, and a crashed iteration can skip
    finish entirely — both must still produce partition-valid records
    (the next begin closes the dangling window as aborted)."""
    sp = StepProfiler()
    sp.begin_iteration(0, 0.0)
    sp.enter("prefill", 0.5)         # never exited
    sp.begin_iteration(1, 2.0)       # auto-closes iter 0
    rec1 = sp.finish_iteration(3.0)
    recs = sp.records()
    assert [r["it"] for r in recs] == [0, 1]
    assert recs[0]["aborted"] is True
    assert recs[0]["phases"] == {"prefill": 1500.0, "other": 500.0}
    _assert_partition(recs)
    assert rec1["device_ms"] == 0.0
    # Device phases roll up separately from host phases.
    assert recs[0]["host_ms"] == 500.0
    assert recs[0]["device_ms"] == 1500.0


def test_phase_hook_is_noop_without_active_iteration():
    """The scoped hook must cost nothing (and record nothing) when no
    profiler is installed or no iteration is open — instrumentation
    sites fire unconditionally on the serving hot path."""
    with stepprof.phase("admit"):
        pass                         # no profiler at all
    sp = stepprof.enable()
    with stepprof.phase("admit"):
        pass                         # profiler idle, no iteration
    assert not sp.has_records()
    sp.begin_iteration(0, 1.0)
    assert sp.active()
    sp.finish_iteration(2.0)
    assert len(sp.records()) == 1


def test_check_partition_rejects_broken_vectors():
    good = {"it": 3, "wall_ms": 10.0,
            "phases": {"admit": 4.0, "other": 6.0},
            "host_bubble_frac": 1.0}
    assert stepprof.check_partition(good) is None
    assert "partition invariant" in stepprof.check_partition(
        {**good, "phases": {"admit": 4.0}})
    assert "missing 'phases'" in stepprof.check_partition(
        {"wall_ms": 1.0})
    assert "outside [0, 1]" in stepprof.check_partition(
        {**good, "host_bubble_frac": 1.7})


# ---------------------------------------------------------------------------
# Iteration shapes (the acceptance criterion's sweep) + determinism.
# ---------------------------------------------------------------------------

def test_plain_decode_partitions_and_is_byte_deterministic(served):
    """Two identically-seeded replays under the injected clock produce
    BYTE-IDENTICAL phase records; every iteration satisfies the
    partition invariant and carries the plain-decode phases."""
    trace = build_trace(LoadSpec(n_requests=2, seed=3,
                                 prompt_len=(4, 4), max_new=(3, 3),
                                 mean_interarrival_iters=0.0))
    recs1, report = _profiled_run(served, trace, max_batch=2,
                                  num_pages=16, prefill_chunk=4)
    recs2, _ = _profiled_run(served, trace, max_batch=2,
                             num_pages=16, prefill_chunk=4)
    assert report["all_finished"]
    _assert_partition(recs1)
    assert json.dumps(recs1) == json.dumps(recs2), \
        "phase records are not byte-deterministic under a fake clock"
    seen = {p for r in recs1 for p in r["phases"]}
    assert {"admit", "decode_dispatch", "device_wait",
            "accounting"} <= seen
    # Cumulative counters are monotone and end at the run totals.
    cums = [r["host_ms_cum"] for r in recs1]
    assert cums == sorted(cums)
    assert cums[-1] == pytest.approx(
        round(sum(r["host_ms"] for r in recs1), 3), abs=0.001)


def test_chunked_prefill_mixed_iterations_partition(served):
    """Prefill slices interleaved with in-flight decode: iterations
    carrying BOTH a prefill slice and a decode batch still partition."""
    trace = build_trace(LoadSpec(n_requests=3, seed=1,
                                 prompt_len=(8, 10), max_new=(3, 4),
                                 mean_interarrival_iters=1.0))
    recs, report = _profiled_run(served, trace, max_batch=4,
                                 num_pages=32, prefill_chunk=4)
    assert report["all_finished"]
    _assert_partition(recs)
    mixed = [r for r in recs if r["phases"].get("prefill", 0) > 0
             and r["phases"].get("decode_dispatch", 0) > 0]
    assert mixed, "no iteration mixed a prefill slice with decode"
    assert all(r["device_ms"] >= r["phases"].get("prefill", 0)
               for r in recs)


def test_preemption_shape_partitions(served):
    """Page pressure forces eviction mid-decode (phase-1 dryrun shape):
    the preempting iterations partition like any other."""
    trace = build_trace(LoadSpec(n_requests=8, seed=0,
                                 mean_interarrival_iters=1.0))
    recs, report = _profiled_run(served, trace, max_batch=4, num_pages=8,
                                 prefill_chunk=4, max_waiting=8)
    assert report["all_finished"]
    assert report["preemptions"] > 0, \
        "pool sizing no longer exercises eviction"
    _assert_partition(recs)
    assert any(r["phases"].get("pages", 0) > 0 for r in recs)


def test_spec_decode_verify_shape_partitions(served):
    """Draft-and-verify iterations (spec_k=2): the draft-planning phase
    appears and the verify launch still splits dispatch/device_wait."""
    trace = [{"req_id": "sp-0", "arrival_iter": 0,
              "prompt": [3, 9] * 4, "max_new_tokens": 5, "priority": 0}]
    recs, report = _profiled_run(served, trace, max_batch=2,
                                 num_pages=16, prefill_chunk=4,
                                 spec_k=2)
    assert report["all_finished"]
    _assert_partition(recs)
    assert any(r["phases"].get("draft", 0) > 0 for r in recs)
    assert any(r["phases"].get("device_wait", 0) > 0 for r in recs)


def test_disagg_migration_advance_partitions(served):
    """The disagg tier's migration-advance slice lands in ``migrate``
    and the extra lifecycle stage keeps the partition."""
    from triton_distributed_tpu.disagg import (
        DisaggServingEngine, role_contexts,
    )

    pctx, dctx = role_contexts(jax.devices()[:2])
    pe = Engine(served.cfg, served.params, pctx, backend="xla",
                max_seq=64)
    de = Engine(served.cfg, served.params, dctx, backend="xla",
                max_seq=64, page_size=4)
    prof = StepProfiler()
    prev = stepprof.set_profiler(prof)
    try:
        se = DisaggServingEngine(pe, de, max_batch=2, num_pages=8,
                                 prefill_chunk=4, block_pages=1,
                                 clock=CounterClock())
        report = run_trace(se, [{"req_id": "mig-0", "arrival_iter": 0,
                                 "prompt": list(range(30, 42)),
                                 "max_new_tokens": 4, "priority": 0}])
    finally:
        stepprof.set_profiler(prev)
    assert se.disagg_active and report["all_finished"]
    recs = prof.records()
    _assert_partition(recs)
    assert any(r["phases"].get("migrate", 0) > 0 for r in recs), \
        "a 3-block migration must spend time in the migrate phase"


def test_evacuation_preflight_shape_partitions(served):
    """A rank loss mid-serve: the evacuation runs inside ``preflight``
    and the geometry-transition iteration still partitions."""
    from triton_distributed_tpu.resilience import (
        clear_rank_loss, mark_rank_lost,
    )

    cfg, params = served.cfg, served.params
    ctx2 = initialize_distributed(mesh_shape=(2,), axis_names=("tp",),
                                  devices=jax.devices()[:2])
    eng = Engine(cfg, params, ctx2, backend="xla", max_seq=64,
                 page_size=4)
    prof = StepProfiler()
    prev = stepprof.set_profiler(prof)
    clear_rank_loss()
    try:
        se = ServingEngine(eng, max_batch=2, prefill_chunk=4,
                           clock=CounterClock())
        se.submit([5, 77, 131, 9, 40, 2], 5, req_id="ev-0")
        for _ in range(3):
            se.step()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            mark_rank_lost(1)
            se.run()
        assert se.evacuated and eng.n_total == 1
    finally:
        clear_rank_loss()
        stepprof.set_profiler(prev)
    recs = prof.records()
    _assert_partition(recs)
    evac = [r for r in recs if r["phases"].get("preflight", 0) > 0]
    assert evac, "the evacuation never charged the preflight phase"


def test_fleet_router_per_replica_records_and_merged_bubble(tmp_path):
    """Fleet replicas step through ONE profiler: records carry replica
    labels, per-replica cumulative counters stay separate, and
    ``publish_metrics`` merges the bubble gauge (fleet mean) plus the
    replica-labeled variants into the fleet registry."""
    from triton_distributed_tpu.fleet import FleetRouter, ReplicaHandle

    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(7), cfg)
    reps = []
    for i in range(2):
        ctx = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                     devices=jax.devices()[:1])
        eng = Engine(cfg, params, ctx, backend="xla", max_seq=64,
                     page_size=4)
        reps.append(ReplicaHandle.build(str(i), eng, max_batch=2,
                                        num_pages=16, prefill_chunk=4,
                                        max_waiting=8))
    obs.start_run(str(tmp_path))
    try:
        router = FleetRouter(reps, policy="round_robin")
        run_trace(router, build_trace(LoadSpec(
            n_requests=2, seed=5, prompt_len=(4, 6), max_new=(3, 4),
            mean_interarrival_iters=0.0)))
        sp = stepprof.get_profiler()
        recs = sp.records()
        labels = sorted({r.get("replica") for r in recs} - {None})
        cum0 = sp.cumulative("0")
        cum1 = sp.cumulative("1")
        snap = obs_metrics.registry().snapshot()
    finally:
        obs.finish_run()
    _assert_partition(recs)
    assert labels == ["0", "1"], \
        f"per-replica attribution lost (labels {labels})"
    assert cum0[0] > 0 and cum1[0] > 0 and cum0 != cum1
    merged = snap.get(obs_metrics.SERVE_HOST_BUBBLE_FRAC)
    assert merged is not None and 0.0 < merged["value"] <= 1.0
    labeled = [k for k in snap
               if k.startswith(obs_metrics.SERVE_HOST_BUBBLE_FRAC + "{")
               and 'replica="' in k]
    assert len(labeled) == 2, labeled
    # The steps lane landed in the run dir with one thread per replica.
    lane = json.load(open(tmp_path / "steps.spans.json"))
    threads = {e["args"]["name"] for e in lane["traceEvents"]
               if e.get("name") == "thread_name"}
    assert threads == {"step-phases/0", "step-phases/1"}


# ---------------------------------------------------------------------------
# Evidence surfaces: registry, flight ring, postmortem, report gate.
# ---------------------------------------------------------------------------

def test_metrics_published_under_obs_run(served, tmp_path):
    """Under an obs run the loop publishes the bubble gauge, the
    host/device step histograms, and per-phase histograms."""
    obs.start_run(str(tmp_path))
    try:
        se = ServingEngine(served, max_batch=2, num_pages=16,
                           prefill_chunk=4)
        se.submit(list(range(1, 8)), 3, req_id="m-0")
        se.run()
        reg = obs_metrics.registry()
        bubble = reg.get(obs_metrics.SERVE_HOST_BUBBLE_FRAC)
        assert bubble is not None and 0.0 < bubble.value <= 1.0
        assert reg.get(obs_metrics.SERVE_STEP_HOST_MS).count > 0
        assert reg.get(obs_metrics.SERVE_STEP_DEVICE_MS).count > 0
        for phase in ("admit", "decode_dispatch", "accounting"):
            h = reg.get(f"{obs_metrics.SERVE_PHASE_MS_PREFIX}_{phase}")
            assert h is not None and h.count > 0, phase
    finally:
        obs.finish_run()
    # The run dir report validates with the steps lane present.
    assert obs_report.main([str(tmp_path), "--check",
                            "--require-series", ""]) == 0


def test_flight_dump_carries_phases_and_postmortem_renders(served,
                                                           tmp_path):
    """Flight-ring iteration records carry the phase vector + the
    cumulative host/device counters; the postmortem renders the phase
    table; ``obs.report --check`` verifies the partition on the dump."""
    from triton_distributed_tpu.obs.slo import SLOConfig

    prior = obs_metrics.set_registry(obs_metrics.Registry())
    prof = StepProfiler()
    prev = stepprof.set_profiler(prof)
    monkey_dir = str(tmp_path)
    os.environ["TDTPU_FLIGHT_DIR"] = monkey_dir
    try:
        se = ServingEngine(served, max_batch=2, num_pages=8,
                           prefill_chunk=4,
                           slo_cfg=SLOConfig(tokens_per_s_min=1e12),
                           clock=CounterClock())
        se.submit(list(range(1, 8)), 3, req_id="fd-0")
        se.run()
        dumps = obs_flight.find_dumps(monkey_dir)
    finally:
        os.environ.pop("TDTPU_FLIGHT_DIR", None)
        stepprof.set_profiler(prev)
        obs_metrics.set_registry(prior)
    assert dumps
    data = obs_flight.load_dump(dumps[0])
    phased = [r for r in data["iterations"] if "phases" in r]
    assert phased, "flight records carry no phase vectors"
    for rec in phased:
        assert stepprof.check_partition(rec) is None
        assert rec["host_ms_cum"] >= rec["host_ms"]
        assert "device_ms_cum" in rec
    rendered = obs_postmortem.render(data, dumps[0])
    assert "step phases (ms; bubble = host/wall):" in rendered
    assert "cumulative: host" in rendered
    assert obs_report.main([str(tmp_path), "--check", "--require-series",
                            "", "--allow-missing-step-profile"]) == 0


def test_report_check_gates_steps_lane_and_partition(tmp_path):
    """A serving-tier snapshot without ``steps.spans.json`` fails
    --check (host-bubble attribution lost); the opt-out or the lane
    passes it; a flight dump whose phase vector breaks the partition
    invariant fails --check even with the lane present."""
    from triton_distributed_tpu.obs.reqtrace import ReqTracer

    reg = obs_metrics.Registry()
    reg.counter(obs_metrics.SERVE_FINISHED, "x").inc(1)
    reg.gauge(obs_metrics.KV_PAGES_RESIDENT, "x").set(4)
    reg.save(str(tmp_path))
    rt = ReqTracer()
    rt.arrival("r-0", 0.0)
    rt.save(str(tmp_path / "requests.spans.json"))
    # The goodput (ISSUE 19) and KV host-tier (ISSUE 20) lanes gate the
    # same way; opt out so this test stays focused on the step-phase lane.
    args = [str(tmp_path), "--check", "--require-series", "",
            "--allow-missing-goodput", "--allow-missing-kv-tier"]
    assert obs_report.main(args) == 1
    assert obs_report.main(args + ["--allow-missing-step-profile"]) == 0
    sp = StepProfiler()
    sp.begin_iteration(0, 1.0)
    sp.finish_iteration(1.5)
    sp.save(str(tmp_path / "steps.spans.json"))
    assert obs_report.main(args) == 0
    # Now a flight dump with a broken phase vector: Σ phases != wall.
    rec = obs_flight.FlightRecorder(capacity=4, run_dir=str(tmp_path))
    rec.record({"iter": 0, "wall_ms": 10.0,
                "phases": {"admit": 1.0, "other": 2.0},
                "host_bubble_frac": 0.3})
    rec.dump("slo_violation", "synthetic partition break", 1)
    assert obs_report.main(args) == 1


# ---------------------------------------------------------------------------
# Satellite 1: deterministic SLO watchdog under the injected clock.
# ---------------------------------------------------------------------------

def test_check_serving_stamps_injected_clock_not_wall_time():
    from triton_distributed_tpu.obs import slo as obs_slo

    reg = obs_metrics.Registry()
    reg.gauge("tdtpu_serve_tokens_per_s", "x").set(5.0)
    clock = CounterClock(step=0.25)
    s1 = obs_slo.check_serving(reg, cfg=obs_slo.SLOConfig(), clock=clock)
    s2 = obs_slo.check_serving(reg, cfg=obs_slo.SLOConfig(), clock=clock)
    assert (s1["t"], s2["t"]) == (0.25, 0.5)
    # Without a clock the section carries NO stamp (never wall time).
    s3 = obs_slo.check_serving(reg, cfg=obs_slo.SLOConfig())
    assert "t" not in s3


def test_rolling_rate_deterministic_under_injected_clock(served):
    """Two identically-seeded serving runs under CounterClock publish
    the SAME rolling tokens/s gauge — the window math reads only the
    injected clock."""
    def one_run():
        prior = obs_metrics.set_registry(obs_metrics.Registry())
        try:
            se = ServingEngine(served, max_batch=2, num_pages=16,
                               prefill_chunk=4, clock=CounterClock())
            se.submit(list(range(1, 6)), 3, req_id="rr-0")
            se.run()
            return se._rolling_rate()
        finally:
            obs_metrics.set_registry(prior)

    r1, r2 = one_run(), one_run()
    assert r1 == r2 and r1 > 0
