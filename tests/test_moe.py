"""MoE tests: TP-MoE (AG+GroupGEMM → MoE+RS) and EP-MoE (AllToAll dispatch)
vs a dense single-device reference on the 8-CPU mesh."""

import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.layers.ep_moe import (
    ep_moe_specs, ep_moe_fwd,
)
from triton_distributed_tpu.ops.moe import moe_tp_fwd
from triton_distributed_tpu.runtime.context import shard_map_on


def _ref_moe(x, router, wg, wu, wd, topk):
    """Dense reference: every token through its top-k experts, fp32."""
    logits = np.asarray(x, np.float64) @ np.asarray(router, np.float64)
    order = np.argsort(-logits, axis=1)[:, :topk]
    out = np.zeros_like(np.asarray(x, np.float64))
    for t in range(x.shape[0]):
        sel = order[t]
        w = np.exp(logits[t, sel] - logits[t, sel].max())
        w = w / w.sum()
        for j, e in enumerate(sel):
            h = np.asarray(x[t], np.float64)
            gate = h @ np.asarray(wg[e], np.float64)
            up = h @ np.asarray(wu[e], np.float64)
            act = gate / (1 + np.exp(-gate)) * up
            out[t] += w[j] * (act @ np.asarray(wd[e], np.float64))
    return out


@pytest.fixture(scope="module")
def moe_case():
    n, E, topk = 8, 16, 2
    m, h, ffn = 64, 64, 128
    rng = np.random.default_rng(0)
    x = rng.standard_normal((m, h)).astype(np.float32) * 0.5
    router = rng.standard_normal((h, E)).astype(np.float32) * 0.2
    wg = rng.standard_normal((E, h, ffn)).astype(np.float32) * h ** -0.5
    wu = rng.standard_normal((E, h, ffn)).astype(np.float32) * h ** -0.5
    wd = rng.standard_normal((E, ffn, h)).astype(np.float32) * ffn ** -0.5
    ref = _ref_moe(x, router, wg, wu, wd, topk)
    return dict(n=n, E=E, topk=topk, x=x, router=router, wg=wg, wu=wu,
                wd=wd, ref=ref)


@pytest.mark.parametrize("mode", ["ring", "overlap", "xla"])
def test_moe_tp_golden(ctx, moe_case, mode):
    """All three gather strategies — ring pipeline (default), sequential
    Pallas AG, lax.all_gather — match the per-token dense golden."""
    c = moe_case
    out = moe_tp_fwd(jnp.asarray(c["x"]), jnp.asarray(c["router"]),
                     jnp.asarray(c["wg"]), jnp.asarray(c["wu"]),
                     jnp.asarray(c["wd"]), c["topk"], ctx, mode=mode)
    np.testing.assert_allclose(np.asarray(out), c["ref"],
                               rtol=2e-3, atol=2e-3)


def test_moe_ep_golden(ctx, moe_case):
    c = moe_case
    n, topk = c["n"], c["topk"]
    params = {"router": jnp.asarray(c["router"]),
              "w_gate": jnp.asarray(c["wg"]),
              "w_up": jnp.asarray(c["wu"]),
              "w_down": jnp.asarray(c["wd"])}
    specs = ep_moe_specs("tp")

    # Tokens data-parallel over ranks: each device routes its own m/n rows.
    fn = shard_map_on(
        ctx,
        lambda p, xl: ep_moe_fwd(p, xl, topk, num_ranks=n),
        (specs, P("tp")), P("tp"))
    out = fn(params, jnp.asarray(c["x"]))
    np.testing.assert_allclose(np.asarray(out), c["ref"],
                               rtol=2e-3, atol=2e-3)


def test_moe_ep_single_rank_matches(moe_case):
    """n=1 path (pure grouped MLP) against the same reference."""
    c = moe_case
    params = {"router": jnp.asarray(c["router"]),
              "w_gate": jnp.asarray(c["wg"]),
              "w_up": jnp.asarray(c["wu"]),
              "w_down": jnp.asarray(c["wd"])}
    out = ep_moe_fwd(params, jnp.asarray(c["x"]), c["topk"], num_ranks=1)
    np.testing.assert_allclose(np.asarray(out), c["ref"],
                               rtol=2e-3, atol=2e-3)


def test_moe_ep_stream_matches_barrier_path(ctx, moe_case):
    """EP-MoE through the barrier-free parity AllToAll (a2a_state threaded,
    dispatch + combine alternating parity over one workspace) is numerically
    identical to the barrier variant across repeated calls."""
    from triton_distributed_tpu.ops.all_to_all import a2a_stream_workspace

    c = moe_case
    n, topk = c["n"], c["topk"]
    m, h = c["x"].shape
    block = 16
    cap = -(-(m // n * topk) // block) * block
    params = {"router": jnp.asarray(c["router"]),
              "w_gate": jnp.asarray(c["wg"]),
              "w_up": jnp.asarray(c["wu"]),
              "w_down": jnp.asarray(c["wd"])}
    specs = ep_moe_specs("tp")

    def run(p, xl):
        ws, idx = a2a_stream_workspace(n, cap, h, xl.dtype)
        outs = []
        for _ in range(3):   # repeated steady-state calls, shared workspace
            y, (ws, idx) = ep_moe_fwd(p, xl, topk, num_ranks=n,
                                      a2a_state=(ws, idx))
            outs.append(y)
        return jnp.stack(outs)

    fn = shard_map_on(ctx, run, (specs, P("tp")), P(None, "tp"))
    outs = np.asarray(fn(params, jnp.asarray(c["x"])))
    for t in range(3):
        np.testing.assert_allclose(outs[t], c["ref"], rtol=2e-3, atol=2e-3)


def test_moe_ep_overflow_reporting(ctx, moe_case):
    """return_overflow surfaces dropped token copies when a caller-supplied
    capacity undercuts m*topk — and reports 0 on the lossless default
    (round-3 advisor: ep_moe_fwd used to discard lay.overflow)."""
    c = moe_case
    n, topk = c["n"], c["topk"]
    m = c["x"].shape[0]
    params = {"router": jnp.asarray(c["router"]),
              "w_gate": jnp.asarray(c["wg"]),
              "w_up": jnp.asarray(c["wu"]),
              "w_down": jnp.asarray(c["wd"])}
    specs = ep_moe_specs("tp")

    def run(cap):
        def body(p, xl):
            y, ov = ep_moe_fwd(p, xl, topk, num_ranks=n, capacity=cap,
                               return_overflow=True)
            return y, ov[None]   # scalar -> per-rank vector for out_specs

        fn = shard_map_on(ctx, body, (specs, P("tp")), (P("tp"), P("tp")))
        y, ov = fn(params, jnp.asarray(c["x"]))
        return y, np.asarray(ov)

    _, ov = run(None)
    assert (ov == 0).all()

    # n=1 path reports structural zero.
    y1, ov1 = ep_moe_fwd(params, jnp.asarray(c["x"]), topk, num_ranks=1,
                         return_overflow=True)
    assert int(ov1) == 0
    np.testing.assert_allclose(np.asarray(y1), c["ref"], rtol=2e-3, atol=2e-3)

    # Starve the slots deterministically: all-ones tokens with a biased
    # router route every copy to rank 0's experts 0/1 (positive logits only
    # for them). 32 tokens/rank * topk 2 = 64 copies to ONE 16-slot cap:
    # the stable expert sort keeps tokens 0..15's expert-0 copies and drops
    # everything else — tokens 16..31 lose BOTH copies.
    biased = np.full_like(c["router"], -10.0)
    biased[:, 0], biased[:, 1] = 10.0, 9.0     # experts 0,1 = rank 0's
    params["router"] = jnp.asarray(biased)

    def body(p, xl):
        y, ov = ep_moe_fwd(p, xl, topk, num_ranks=n, capacity=16,
                           return_overflow=True)
        return y, ov[None]

    fn = shard_map_on(ctx, body, (specs, P("tp")), (P("tp"), P("tp")))
    h = c["x"].shape[1]
    ones = jnp.ones((32 * n, h), jnp.float32)
    y_tight, ov_tight = fn(params, ones)
    assert (np.asarray(ov_tight) == 48).all()   # 64 copies, 16 slots
    y_np = np.asarray(y_tight).reshape(n, 32, h)
    # Dropped copies must contribute ZERO — before the round-4 fix their
    # clamped gather pulled slot 15's (another token's) output.
    np.testing.assert_array_equal(y_np[:, 16:], 0.0)
    assert np.abs(y_np[:, :16]).max() > 0

    # Unit-level clamp contract: every copy to one destination, cap holds
    # half — overflow reports the drop AND the advertised splits shrink to
    # what the slot holds (they used to claim the unclamped count, walking
    # the receiver past the buffer).
    from triton_distributed_tpu.ops.all_to_all import dispatch_layout

    toks = jnp.asarray(np.arange(32 * 4, dtype=np.float32).reshape(32, 4))
    lay = dispatch_layout(toks, jnp.zeros((32,), jnp.int32),
                          num_experts=c["E"], num_ranks=n, cap=16)
    assert int(lay.overflow) == 16
    assert int(lay.send_splits.sum()) == 16
    assert (np.asarray(lay.send_splits)[0] <= 16).all()


def test_moe_reduce_rs_overlap_matches_sequential(ctx, moe_case):
    """The overlapped tail (RS hops under later chunks' down-proj GEMMs)
    must produce the same row-sharded result as the sequential
    grouped-GEMM → combine → ring-RS path."""
    from triton_distributed_tpu.ops.moe import (
        grouped_mlp_gate_up, moe_reduce_rs_local,
        moe_reduce_rs_overlap_local, route_and_sort,
    )

    c = moe_case
    n, topk = c["n"], c["topk"]
    M = c["x"].shape[0]

    def tail(x, router, wg, wu, wd, overlap):
        x_sorted, sort_idx, gsz, _, tw = route_and_sort(x, router, topk)
        act = grouped_mlp_gate_up(x_sorted, gsz, wg, wu)
        if overlap:
            return moe_reduce_rs_overlap_local(
                act, sort_idx, gsz, wd, tw.astype(x.dtype), M,
                axis="tp", num_ranks=n)
        return moe_reduce_rs_local(
            act, sort_idx, gsz, wd, tw.astype(x.dtype), M,
            axis="tp", num_ranks=n, mode="overlap")

    args = tuple(jnp.asarray(c[k]) for k in ("x", "router", "wg", "wu",
                                             "wd"))
    specs = (P(), P(), P(None, None, "tp"), P(None, None, "tp"),
             P(None, "tp", None))
    seq = shard_map_on(ctx, lambda *a: tail(*a, overlap=False),
                       specs, P("tp"))(*args)
    ovl = shard_map_on(ctx, lambda *a: tail(*a, overlap=True),
                       specs, P("tp"))(*args)
    np.testing.assert_allclose(np.asarray(ovl), np.asarray(seq),
                               rtol=1e-4, atol=1e-4)


def test_ag_group_gemm_ring_matches_sequential(ctx, moe_case):
    """Per-source-readiness AG+GroupGEMM returns the identical global
    expert-sorted output as the gather-then-compute form."""
    from triton_distributed_tpu.ops.moe import (
        ag_group_gemm_local, ag_group_gemm_ring_local,
    )

    c = moe_case
    n, topk, E = c["n"], c["topk"], c["E"]
    M = c["x"].shape[0]
    rng = np.random.default_rng(42)
    expert_ids = jnp.asarray(
        rng.integers(0, E, size=(M * topk,)), jnp.int32)
    tw = jnp.asarray(rng.random((M, topk)), jnp.float32)

    def run(xl, ring):
        fn = ag_group_gemm_ring_local if ring else ag_group_gemm_local
        y, sidx, gsz = fn(xl, expert_ids, jnp.asarray(c["wg"]), tw,
                          axis="tp", num_ranks=n)
        return y, sidx, gsz

    x = jnp.asarray(c["x"])
    specs_in = P("tp")
    specs_out = (P(), P(), P())
    y0, s0, g0 = shard_map_on(ctx, lambda xl: run(xl, False),
                              specs_in, specs_out)(x)
    y1, s1, g1 = shard_map_on(ctx, lambda xl: run(xl, True),
                              specs_in, specs_out)(x)
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))
    np.testing.assert_array_equal(np.asarray(g0), np.asarray(g1))
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-4, atol=1e-4)
