"""MoE tests: TP-MoE (AG+GroupGEMM → MoE+RS) and EP-MoE (AllToAll dispatch)
vs a dense single-device reference on the 8-CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.layers.ep_moe import (
    init_ep_moe, ep_moe_specs, ep_moe_fwd,
)
from triton_distributed_tpu.ops.moe import moe_tp_fwd
from triton_distributed_tpu.runtime.context import shard_map_on


def _ref_moe(x, router, wg, wu, wd, topk):
    """Dense reference: every token through its top-k experts, fp32."""
    logits = np.asarray(x, np.float64) @ np.asarray(router, np.float64)
    order = np.argsort(-logits, axis=1)[:, :topk]
    out = np.zeros_like(np.asarray(x, np.float64))
    for t in range(x.shape[0]):
        sel = order[t]
        w = np.exp(logits[t, sel] - logits[t, sel].max())
        w = w / w.sum()
        for j, e in enumerate(sel):
            h = np.asarray(x[t], np.float64)
            gate = h @ np.asarray(wg[e], np.float64)
            up = h @ np.asarray(wu[e], np.float64)
            act = gate / (1 + np.exp(-gate)) * up
            out[t] += w[j] * (act @ np.asarray(wd[e], np.float64))
    return out


@pytest.fixture(scope="module")
def moe_case():
    n, E, topk = 8, 16, 2
    m, h, ffn = 64, 64, 128
    rng = np.random.default_rng(0)
    x = rng.standard_normal((m, h)).astype(np.float32) * 0.5
    router = rng.standard_normal((h, E)).astype(np.float32) * 0.2
    wg = rng.standard_normal((E, h, ffn)).astype(np.float32) * h ** -0.5
    wu = rng.standard_normal((E, h, ffn)).astype(np.float32) * h ** -0.5
    wd = rng.standard_normal((E, ffn, h)).astype(np.float32) * ffn ** -0.5
    ref = _ref_moe(x, router, wg, wu, wd, topk)
    return dict(n=n, E=E, topk=topk, x=x, router=router, wg=wg, wu=wu,
                wd=wd, ref=ref)


@pytest.mark.parametrize("mode", ["ring", "overlap", "xla"])
def test_moe_tp_golden(ctx, moe_case, mode):
    """All three gather strategies — ring pipeline (default), sequential
    Pallas AG, lax.all_gather — match the per-token dense golden."""
    c = moe_case
    out = moe_tp_fwd(jnp.asarray(c["x"]), jnp.asarray(c["router"]),
                     jnp.asarray(c["wg"]), jnp.asarray(c["wu"]),
                     jnp.asarray(c["wd"]), c["topk"], ctx, mode=mode)
    np.testing.assert_allclose(np.asarray(out), c["ref"],
                               rtol=2e-3, atol=2e-3)


def test_moe_ep_golden(ctx, moe_case):
    c = moe_case
    n, topk = c["n"], c["topk"]
    params = {"router": jnp.asarray(c["router"]),
              "w_gate": jnp.asarray(c["wg"]),
              "w_up": jnp.asarray(c["wu"]),
              "w_down": jnp.asarray(c["wd"])}
    specs = ep_moe_specs("tp")

    # Tokens data-parallel over ranks: each device routes its own m/n rows.
    fn = shard_map_on(
        ctx,
        lambda p, xl: ep_moe_fwd(p, xl, topk, num_ranks=n),
        (specs, P("tp")), P("tp"))
    out = fn(params, jnp.asarray(c["x"]))
    np.testing.assert_allclose(np.asarray(out), c["ref"],
                               rtol=2e-3, atol=2e-3)


def test_moe_ep_single_rank_matches(moe_case):
    """n=1 path (pure grouped MLP) against the same reference."""
    c = moe_case
    params = {"router": jnp.asarray(c["router"]),
              "w_gate": jnp.asarray(c["wg"]),
              "w_up": jnp.asarray(c["wu"]),
              "w_down": jnp.asarray(c["wd"])}
    out = ep_moe_fwd(params, jnp.asarray(c["x"]), c["topk"], num_ranks=1)
    np.testing.assert_allclose(np.asarray(out), c["ref"],
                               rtol=2e-3, atol=2e-3)


def test_moe_ep_stream_matches_barrier_path(ctx, moe_case):
    """EP-MoE through the barrier-free parity AllToAll (a2a_state threaded,
    dispatch + combine alternating parity over one workspace) is numerically
    identical to the barrier variant across repeated calls."""
    from triton_distributed_tpu.ops.all_to_all import a2a_stream_workspace

    c = moe_case
    n, topk = c["n"], c["topk"]
    m, h = c["x"].shape
    block = 16
    cap = -(-(m // n * topk) // block) * block
    params = {"router": jnp.asarray(c["router"]),
              "w_gate": jnp.asarray(c["wg"]),
              "w_up": jnp.asarray(c["wu"]),
              "w_down": jnp.asarray(c["wd"])}
    specs = ep_moe_specs("tp")

    def run(p, xl):
        ws, idx = a2a_stream_workspace(n, cap, h, xl.dtype)
        outs = []
        for _ in range(3):   # repeated steady-state calls, shared workspace
            y, (ws, idx) = ep_moe_fwd(p, xl, topk, num_ranks=n,
                                      a2a_state=(ws, idx))
            outs.append(y)
        return jnp.stack(outs)

    fn = shard_map_on(ctx, run, (specs, P("tp")), P(None, "tp"))
    outs = np.asarray(fn(params, jnp.asarray(c["x"])))
    for t in range(3):
        np.testing.assert_allclose(outs[t], c["ref"], rtol=2e-3, atol=2e-3)
