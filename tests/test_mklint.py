"""mklint + page-audit: the seeded-violation matrix (ISSUE 16).

Every hazard/lifetime class the verifiers claim to catch is seeded here
and must surface under its NAMED kind — a checker that goes quiet on a
planted bug is worse than none. Clean paths ride along: the real
builder compositions must lint clean, and a full allocator lifecycle
must audit clean.
"""

import copy
import types

import numpy as np
import pytest

from triton_distributed_tpu.analysis.mklint import (
    check_compiled,
    check_paged_step,
)
from triton_distributed_tpu.analysis.page_audit import (
    PageAuditor,
    replay_iterations,
)
from triton_distributed_tpu.megakernel.builder import MegaKernelBuilder
from triton_distributed_tpu.megakernel.scheduler import (
    ScheduleCycleError,
    topo_schedule,
)
from triton_distributed_tpu.megakernel.tasks import TILE, TaskType

K8 = MegaKernelBuilder._K8_HAZARD


def kinds(report):
    return [v.kind for v in report.violations]


def synth(rows, *, task_rows=None, reads=None, writes=None, edges=(),
          mat_specs=()):
    """A minimal compiled-artifact stand-in: ``rows`` is the queue's
    word-0 type column; hazard metadata defaults to empty per task."""
    n = len(rows)
    q = np.zeros((n, 10), np.int32)
    for i, r in enumerate(rows):
        q[i] = r if isinstance(r, (list, tuple)) else [r] + [0] * 9
    return types.SimpleNamespace(
        queue=q, num_exec=n,
        task_rows=list(task_rows if task_rows is not None else range(n)),
        task_reads=tuple(reads or [()] * n),
        task_writes=tuple(writes or [()] * n),
        hazard_edges=tuple(edges), mat_specs=tuple(mat_specs))


# ---------------------------------------------------------------------------
# Seeded compiled-artifact violations.
# ---------------------------------------------------------------------------

GEMM = int(TaskType.GEMM)


class TestSeededCompiled:
    def test_missing_producer(self):
        # Task 1 reads tile 7, task 0 writes it — but the embedded order
        # runs the reader FIRST (rows swapped).
        comp = synth([GEMM, GEMM], task_rows=[1, 0],
                     writes=[(7,), ()], reads=[(), (7,)],
                     edges=[(0, 1)])
        ks = kinds(check_compiled(comp))
        assert "missing-producer" in ks
        assert "edge-order" in ks

    def test_waw_hazard(self):
        comp = synth([GEMM, GEMM], task_rows=[1, 0],
                     writes=[(7,), (7,)])
        assert "waw-hazard" in kinds(check_compiled(comp))

    def test_kv8_war_hazard(self):
        # The fp8-KV pool alias space: a reader of kv8-tile 5 scheduled
        # AFTER the overwriting append — the WAR the offset spaces exist
        # to order.
        tile = K8 | 5
        comp = synth([int(TaskType.ATTN_DECODE_PAGED_F8),
                      int(TaskType.APPEND_KV_F8)],
                     task_rows=[1, 0],
                     reads=[(tile,), ()], writes=[(), (tile,)])
        assert "kv8-war-hazard" in kinds(check_compiled(comp))

    def test_schedule_divergence(self):
        # Hazards all hold (no shared tiles) but the embedded order is
        # not the canonical Kahn order — the cross-rank positional
        # protocol still breaks.
        comp = synth([GEMM, GEMM], task_rows=[1, 0])
        assert "schedule-divergence" in kinds(check_compiled(comp))

    def test_schedule_cycle(self):
        comp = synth([GEMM, GEMM], edges=[(0, 1), (1, 0)])
        assert "schedule-cycle" in kinds(check_compiled(comp))

    def test_prefetch_retarget(self):
        # Two PREFETCHes with no consuming warm GEMM_WIDE between them:
        # the second clobbers the reserved slot mid-flight.
        comp = synth([int(TaskType.PREFETCH), int(TaskType.PREFETCH)])
        ks = kinds(check_compiled(comp))
        assert "prefetch-retarget" in ks
        assert "prefetch-unconsumed" in ks

    def test_prefetch_missing(self):
        # A warm-consuming GEMM_WIDE (c0 == 1) with no pending prefetch.
        comp = synth([[int(TaskType.GEMM_WIDE)] + [0] * 7 + [1, 0]])
        assert "prefetch-missing" in kinds(check_compiled(comp))

    def test_no_hazard_metadata(self):
        comp = synth([GEMM])
        comp.task_reads = None
        assert kinds(check_compiled(comp)) == ["no-hazard-metadata"]

    def test_clean_synthetic(self):
        comp = synth([GEMM, GEMM], writes=[(7,), ()], reads=[(), (7,)],
                     edges=[(0, 1)])
        assert check_compiled(comp).ok


class TestScheduleCycleError:
    def test_names_cycle_tasks_and_types(self):
        types_ = [TaskType.RMS_NORM, TaskType.GEMM, TaskType.SILU_MUL]
        with pytest.raises(ScheduleCycleError) as ei:
            topo_schedule(3, [(0, 1), (1, 2), (2, 1)], task_types=types_)
        msg = str(ei.value)
        assert "cycle" in msg
        # The cycle members appear by id AND type name.
        assert "1:GEMM" in msg and "2:SILU_MUL" in msg
        assert set(ei.value.cycle) == {1, 2}

    def test_acyclic_unchanged(self):
        order = topo_schedule(3, [(0, 1), (1, 2)])
        assert list(order) == [0, 1, 2]


# ---------------------------------------------------------------------------
# Seeded paged-step violations (real decoder, mutated step state).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def paged():
    """A real PagedMegakernelDecoder + allocator after one retargeted
    step — the seeded tests mutate COPIES of its state."""
    import jax

    from triton_distributed_tpu.analysis.mklint import _tiny_cfg
    from triton_distributed_tpu.megakernel.serving import (
        PagedMegakernelDecoder,
    )
    from triton_distributed_tpu.models.dense import init_dense_llm
    from triton_distributed_tpu.models.kv_cache import PageAllocator

    cfg = _tiny_cfg()
    params = init_dense_llm(jax.random.PRNGKey(0), cfg)
    dec = PagedMegakernelDecoder(cfg, params, num_slots=2, num_pages=4,
                                 max_pages=2)
    alloc = PageAllocator(dec.num_pages + 1, dec.max_pages,
                          reserved=(dec.scratch,))
    pages_a = alloc.alloc_pages("a", 2)
    pages_b = alloc.alloc_pages("b", 1)
    dec._retarget([TILE + 1, 5], [pages_a, pages_b + [-1]], None)
    return dec, alloc, pages_a, pages_b


def mutated(dec, **edits):
    """Deep-copy the decoder's last retarget state and apply edits via a
    callback receiving the queue array."""
    state = copy.deepcopy(dec.last_retarget)
    edits.pop("edit")(np.asarray(state["queue"]), state)
    return state


def test_paged_clean(paged):
    dec, alloc, *_ = paged
    assert check_paged_step(dec, ref_counts=alloc).ok


def test_append_shared_page(paged):
    dec, alloc, pages_a, _ = paged
    # Refcount 2 on the page position kv_len falls into: COW never ran.
    target = pages_a[(TILE + 1) // TILE]
    alloc.incref(target)
    try:
        ks = kinds(check_paged_step(dec, ref_counts=alloc))
    finally:
        alloc.decref(target)
    assert "append-shared-page" in ks


def test_table_freed_page(paged):
    dec, alloc, pages_a, _ = paged
    # A table entry the read walks, but with zero live references.
    rc = {p: 1 for p in pages_a}
    rc[pages_a[0]] = 0
    ks = kinds(check_paged_step(dec, ref_counts=rc))
    assert "table-freed-page" in ks


def test_append_scratch(paged):
    dec, alloc, *_ = paged

    def edit(q, state):
        row, kt0, v0 = dec._append_rows[0][0]
        q[row, 1] = kt0 + dec.scratch
        q[row, 3] = v0 + dec.scratch
    state = mutated(dec, edit=edit)
    assert "append-scratch" in kinds(
        check_paged_step(dec, state, ref_counts=alloc))


def test_append_out_of_bounds(paged):
    dec, alloc, *_ = paged

    def edit(q, state):
        row, kt0, v0 = dec._append_rows[0][0]
        q[row, 1] = kt0 + dec.scratch + 3
        q[row, 3] = v0 + dec.scratch + 3
    state = mutated(dec, edit=edit)
    assert "append-out-of-bounds" in kinds(
        check_paged_step(dec, state, ref_counts=alloc))


def test_append_retarget(paged):
    dec, alloc, pages_a, _ = paged

    def edit(q, state):
        # Redirect the append to a page the table maps elsewhere.
        row, kt0, v0 = dec._append_rows[0][0]
        wrong = pages_a[0]          # position kv_len lives on pages_a[1]
        q[row, 1] = kt0 + wrong
        q[row, 3] = v0 + wrong
    state = mutated(dec, edit=edit)
    assert "append-retarget" in kinds(
        check_paged_step(dec, state, ref_counts=None))


def test_table_row_skew(paged):
    dec, alloc, *_ = paged

    def edit(q, state):
        _row, kt0, v0, trow = dec._attn_rows[0][0]
        flat = q[trow:trow + dec._table_rows].reshape(-1)
        flat[1] += 1                # V half points one page off
    state = mutated(dec, edit=edit)
    assert "table-row-skew" in kinds(
        check_paged_step(dec, state, ref_counts=None))


def test_table_scratch_read(paged):
    dec, alloc, *_ = paged

    def edit(q, state):
        _row, kt0, v0, trow = dec._attn_rows[0][0]
        flat = q[trow:trow + dec._table_rows].reshape(-1)
        flat[0] = kt0 + dec.scratch     # entry 0 is walked (ktiles >= 1)
        flat[1] = v0 + dec.scratch
    state = mutated(dec, edit=edit)
    assert "table-scratch-read" in kinds(
        check_paged_step(dec, state, ref_counts=None))


def test_kv_state_mismatch(paged):
    dec, alloc, *_ = paged

    def edit(q, state):
        row = dec._attn_rows[0][0][0]
        q[row, 6] += 3              # valid-length word lies about kv_len
    state = mutated(dec, edit=edit)
    assert "kv-state-mismatch" in kinds(
        check_paged_step(dec, state, ref_counts=None))


def test_spec_window_mismatch():
    import jax

    from triton_distributed_tpu.analysis.mklint import _tiny_cfg
    from triton_distributed_tpu.megakernel.serving import (
        PagedMegakernelDecoder,
    )
    from triton_distributed_tpu.models.dense import init_dense_llm

    cfg = _tiny_cfg()
    params = init_dense_llm(jax.random.PRNGKey(0), cfg)
    dec = PagedMegakernelDecoder(cfg, params, num_slots=2, num_pages=4,
                                 max_pages=2, spec_window=3)
    dec._retarget([TILE - 1, 5], [[0, 1], [2, -1]], [2, 1])
    assert check_paged_step(dec, ref_counts=None).ok
    state = copy.deepcopy(dec.last_retarget)
    q = np.asarray(state["queue"])
    q[dec._attn_rows[0][0][0], 5] += 1     # folded window != live window
    assert "spec-window-mismatch" in kinds(
        check_paged_step(dec, state, ref_counts=None))


# ---------------------------------------------------------------------------
# The real builder compositions must lint clean.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("comp_name", ["decode_n1_dense", "serving_paged"])
def test_compositions_clean(comp_name):
    from triton_distributed_tpu.analysis.mklint import COMPOSITIONS

    rep = COMPOSITIONS[comp_name]()
    assert rep.ok, [v.to_json() for v in rep.violations]
    assert rep.n_tasks > 0 and rep.n_edges > 0


def test_real_builder_exports_hazard_metadata():
    mb = MegaKernelBuilder()
    h = 256
    x, w, out = mb.tensor(TILE, h), mb.tensor(h, h), mb.tensor(TILE, h)
    mb.gemm(out, x, w)
    comp = mb.compile()
    assert comp.hazard_edges is not None
    assert len(comp.task_reads) == len(comp.task_writes)
    assert check_compiled(comp).ok


# ---------------------------------------------------------------------------
# Page auditor: seeded lifetime violations + clean lifecycle.
# ---------------------------------------------------------------------------

class TestPageAuditor:
    def test_clean_lifecycle(self):
        aud = PageAuditor(4)
        aud.record({"op": "alloc", "owner": "a", "pages": [0, 1]})
        aud.record({"op": "share", "owner": "b", "pages": [0]})
        aud.record({"op": "incref", "page": 1})
        aud.record({"op": "cow", "owner": "b", "old": 0, "new": 2})
        aud.record({"op": "decref", "page": 0})
        aud.note_launch([0, 1], [2], site="decode")
        assert aud.end_iteration({"a": 8, "b": 4}) != []
        aud.record({"op": "free", "owner": "b", "pages": [2]})
        aud.record({"op": "decref", "page": 2})
        aud.record({"op": "decref", "page": 1})   # drops b's extra ref
        aud.record({"op": "free", "owner": "a", "pages": [0, 1]})
        aud.record({"op": "decref", "page": 0})
        aud.record({"op": "decref", "page": 1})
        aud.end_iteration({})
        assert aud.report().ok, [v.to_json() for v in aud.violations]

    def test_leak_dead_owner(self):
        aud = PageAuditor(4)
        aud.record({"op": "alloc", "owner": "r0", "pages": [0]})
        aud.end_iteration({})                 # r0 no longer live
        assert "leak" in [v.kind for v in aud.violations]

    def test_leak_over_baseline(self):
        aud = PageAuditor(4)
        aud.record({"op": "alloc", "owner": "r0", "pages": [0, 1, 2, 3]})
        aud.end_iteration({"r0": 4})          # kv_len 4 -> 1 page (+1)
        assert "leak" in [v.kind for v in aud.violations]

    def test_double_free(self):
        aud = PageAuditor(4)
        aud.record({"op": "alloc", "owner": "r0", "pages": [0]})
        aud.record({"op": "decref", "page": 0})
        aud.record({"op": "decref", "page": 0})
        assert "double-free" in [v.kind for v in aud.violations]

    def test_use_after_free_share(self):
        aud = PageAuditor(4)
        aud.record({"op": "alloc", "owner": "r0", "pages": [0]})
        aud.record({"op": "decref", "page": 0})
        aud.record({"op": "share", "owner": "r1", "pages": [0]})
        assert "use-after-free" in [v.kind for v in aud.violations]

    def test_use_after_free_launch(self):
        aud = PageAuditor(4)
        aud.record({"op": "alloc", "owner": "r0", "pages": [0]})
        aud.record({"op": "decref", "page": 0})
        aud.note_launch([0], [], site="decode iter 1")
        vs = aud.violations
        assert [v.kind for v in vs] == ["use-after-free"]
        assert "freed this iteration" in vs[0].message

    def test_cow_before_append(self):
        aud = PageAuditor(4)
        aud.record({"op": "alloc", "owner": "r0", "pages": [0]})
        aud.record({"op": "incref", "page": 0})   # a sharer still reads
        aud.note_launch([], [0], site="decode iter 1")
        assert "cow-before-append" in [v.kind for v in aud.violations]

    def test_audit_desync(self):
        aud = PageAuditor(4)
        aud.record({"op": "alloc", "owner": "r0", "pages": [0]})
        aud.record({"op": "alloc", "owner": "r1", "pages": [0]})
        assert "audit-desync" in [v.kind for v in aud.violations]

    def test_violation_cap(self):
        aud = PageAuditor(4, max_violations=3)
        for _ in range(5):
            aud.record({"op": "decref", "page": 9})
        assert len(aud.violations) == 3
        assert aud.n_suppressed == 2
        assert aud.summary()["n_suppressed"] == 2


class TestReplay:
    def test_replay_uses_embedded_page_size(self):
        recs = [{"iter": 1, "page_size": 4,
                 "page_events": [{"op": "alloc", "owner": "r0",
                                  "pages": [0, 1, 2]}],
                 "page_live": {"r0": 12}}]
        aud = replay_iterations(recs)
        assert aud.page_size == 4
        assert aud.report().ok

    def test_replay_flags_recorded_leak(self):
        recs = [{"iter": 1, "page_size": 4,
                 "page_events": [{"op": "alloc", "owner": "r0",
                                  "pages": [0]}],
                 "page_live": {}}]
        assert not replay_iterations(recs).report().ok

    def test_warm_start_tolerates_pre_ring_refs(self):
        # Ring rolled past iteration 1: a decref of a page allocated
        # before the window is a pre-ring reference, not a double-free.
        recs = [{"iter": 7, "page_size": 4,
                 "page_events": [{"op": "decref", "page": 3}],
                 "page_live": {}}]
        aud = replay_iterations(recs)
        assert aud.warm_start and aud.report().ok
        # ...but an IN-window double release still flags.
        recs[0]["page_events"].append({"op": "decref", "page": 3})
        assert "double-free" in [
            v.kind for v in replay_iterations(recs).violations]


# ---------------------------------------------------------------------------
# The live serving integration (TDTPU_PAGE_AUDIT=1).
# ---------------------------------------------------------------------------

def test_serving_engine_audits_clean(monkeypatch):
    import jax
    import jax.numpy as jnp
    import numpy as _np

    from triton_distributed_tpu.models import (
        Engine, init_dense_llm, tiny_config,
    )
    from triton_distributed_tpu.runtime import initialize_distributed
    from triton_distributed_tpu.serving.loop import ServingEngine

    monkeypatch.setenv("TDTPU_PAGE_AUDIT", "1")
    cfg = tiny_config()
    params = init_dense_llm(jax.random.key(0), cfg)
    ctx = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                 devices=jax.devices()[:1])
    eng = Engine(cfg, params, ctx, backend="xla", max_seq=64, page_size=4)
    se = ServingEngine(eng, max_batch=2, num_pages=6, prefill_chunk=4)
    assert se.page_audit is not None
    golden = _np.asarray(eng.serve(
        jnp.asarray([list(range(1, 8))], jnp.int32), gen_len=6))[0].tolist()
    r, _ = se.submit(list(range(1, 8)), 6)
    se.run()
    assert r.tokens == golden
    assert se.page_audit.report().ok, [
        v.to_json() for v in se.page_audit.violations]
    assert se.page_audit.n_events > 0
    # The flight ride-alongs are populated for the offline replay.
    assert se._last_page_live == {} or isinstance(se._last_page_live, dict)
