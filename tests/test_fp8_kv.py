"""fp8 (e4m3) KV cache end-to-end — round 12 (ROADMAP 1a).

The KV stream is the decode-bandwidth bound at serving scale: these
tests pin the fp8 storage mode's correctness contract across every
layer that touches KV bytes —

* ``ops/paged_attention``: e4m3 pools, saturating append (the
  ``models/fp8._to_e4m3`` ±448 clamp), EXACT parity vs the golden under
  quantize-then-attend (both paths read the same stored e4m3 values);
* ``models/kv_cache``: the fixed-HBM budget accounting — e4m3 page
  tiles cost half the bf16 bytes, so ``num_pages`` doubles at the same
  budget (the admission-width lever);
* ``models/engine`` + ``serving/loop``: the kv_dtype flow (to_paged /
  chunked-prefill scatter quantize identically → sequential and
  continuous-batching serves stay token-identical), the
  ``tdtpu_kv_pages_resident`` gauge, preempt/resume on the fp8 pool;
* the megakernel paged lane: ATTN_DECODE_PAGED_F8 / APPEND_KV_F8 —
  token parity with the dense fp8-KV path, named errors for the
  unsupported combos;
* drift: argmax stability and bounded logits drift vs full-width KV
  over 64 teacher-forced decode steps.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.models import init_dense_llm, tiny_config
from triton_distributed_tpu.models.config import ModelConfig
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.kv_cache import (
    PagePoolConfigError, init_paged_model_cache, kv_page_bytes,
    kv_pool_pages_for_budget,
)
from triton_distributed_tpu.ops.paged_attention import (
    init_paged_kv_cache, paged_append, paged_decode_attention,
    paged_decode_attention_golden,
)
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving.loop import ServingEngine

E8 = jnp.float8_e4m3fn


@pytest.fixture(scope="module")
def ctx1():
    return initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])


@pytest.fixture(scope="module")
def tiny_model():
    cfg = tiny_config()
    return cfg, init_dense_llm(jax.random.key(0), cfg)


@pytest.fixture(scope="module")
def mk_model():
    cfg = ModelConfig(hidden_size=256, intermediate_size=256, num_layers=1,
                      num_heads=2, num_kv_heads=1, head_dim=128,
                      vocab_size=512, qk_norm=True, dtype="float32")
    return cfg, init_dense_llm(jax.random.PRNGKey(1), cfg)


# ---------------------------------------------------------------------------
# ops/paged_attention: e4m3 pools.
# ---------------------------------------------------------------------------

def _filled_fp8_cache(rng, *, batch=2, hkv=2, d=128, page=8, max_pages=3,
                      num_pages=6, tokens=10, hot_at=None):
    cache = init_paged_kv_cache(batch, num_pages=num_pages,
                                page_size=page, num_kv_heads=hkv,
                                head_dim=d, max_pages=max_pages,
                                kv_dtype=E8)
    for t in range(tokens):
        k = jnp.asarray(rng.standard_normal((batch, hkv, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((batch, hkv, d)), jnp.float32)
        if hot_at is not None and t == hot_at:
            k = k.at[0, 0, 0].set(999.0)
            v = v.at[0, 0, 1].set(-999.0)
        cache = paged_append(cache, k, v)
    return cache


def test_fp8_paged_decode_matches_quantized_golden():
    """Quantize-then-attend parity is EXACT (not approximate): the
    kernel and the golden read the same stored e4m3 pool values and
    both accumulate in >= fp32."""
    rng = np.random.default_rng(0)
    cache = _filled_fp8_cache(rng)
    assert cache.k_pool.dtype == E8 and cache.v_pool.dtype == E8
    q = jnp.asarray(rng.standard_normal((2, 4, 128)), jnp.float32)
    out = paged_decode_attention(q, cache)
    gold = paged_decode_attention_golden(q, cache)
    np.testing.assert_allclose(np.asarray(out, np.float64), gold,
                               rtol=2e-5, atol=2e-5)


def test_fp8_append_saturates_hot_kv():
    """The ±448 e4m3 clamp MUST apply on append: a plain cast NaNs past
    the finite range and one hot KV element would poison every later
    softmax over its page (the models/fp8._to_e4m3 contract)."""
    rng = np.random.default_rng(1)
    cache = _filled_fp8_cache(rng, hot_at=3)
    kp = np.asarray(cache.k_pool.astype(jnp.float32))
    vp = np.asarray(cache.v_pool.astype(jnp.float32))
    assert np.isfinite(kp).all() and np.isfinite(vp).all()
    assert kp.max() == 448.0 and vp.min() == -448.0
    # Attention over the saturated cache stays finite and matches the
    # golden (which reads the same clamped values).
    q = jnp.asarray(rng.standard_normal((2, 4, 128)), jnp.float32)
    out = np.asarray(paged_decode_attention(q, cache), np.float64)
    assert np.isfinite(out).all()
    np.testing.assert_allclose(
        out, paged_decode_attention_golden(q, cache), rtol=2e-5,
        atol=2e-5)


# ---------------------------------------------------------------------------
# models/kv_cache: fixed-HBM budget accounting.
# ---------------------------------------------------------------------------

def test_kv_pool_doubles_at_fixed_hbm_budget(tiny_model):
    cfg, _ = tiny_model
    budget = 16 * kv_page_bytes(cfg, page_size=4, kv_dtype=jnp.bfloat16)
    bf16 = kv_pool_pages_for_budget(cfg, page_size=4, hbm_bytes=budget,
                                    kv_dtype=jnp.bfloat16)
    f8 = kv_pool_pages_for_budget(cfg, page_size=4, hbm_bytes=budget,
                                  kv_dtype=E8)
    f32 = kv_pool_pages_for_budget(cfg, page_size=4, hbm_bytes=budget)
    assert f8 == 2 * bf16            # half-size page tiles
    assert f8 == 4 * f32             # tiny_config model dtype is f32
    with pytest.raises(PagePoolConfigError, match="kv_hbm_budget"):
        kv_pool_pages_for_budget(cfg, page_size=4, hbm_bytes=1,
                                 kv_dtype=E8)


def test_serving_budget_flows_into_admission(tiny_model, ctx1):
    """ServingEngine(kv_hbm_budget=...) sizes the pool from the budget
    at the engine's kv_dtype; the scheduler's admission math picks the
    wider pool up with no logic change (usable_pages grows)."""
    cfg, params = tiny_model
    budget = 8 * kv_page_bytes(cfg, page_size=4)      # 8 f32 pages
    wide = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                  page_size=4)
    narrow = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4, kv_dtype=E8)
    se_wide = ServingEngine(wide, max_batch=2, kv_hbm_budget=budget)
    se_f8 = ServingEngine(narrow, max_batch=2, kv_hbm_budget=budget)
    assert se_f8.num_pages == 4 * se_wide.num_pages
    assert se_f8.sched.allocator.usable_pages \
        == 4 * se_wide.sched.allocator.usable_pages
    assert se_f8._cache.k_pools.dtype == E8
    with pytest.raises(ValueError, match="num_pages OR kv_hbm_budget"):
        ServingEngine(narrow, max_batch=2, num_pages=4,
                      kv_hbm_budget=budget)


def test_kv_dtype_requires_page_size(tiny_model, ctx1):
    cfg, params = tiny_model
    with pytest.raises(ValueError, match="kv_dtype without page_size"):
        Engine(cfg, params, ctx1, backend="xla", max_seq=64, kv_dtype=E8)


def test_to_paged_saturates_hot_linear_cache(tiny_model, ctx1):
    """Engine.to_paged is the linear→paged quantization point: a hot
    value in the full-width prefill cache must clamp, never NaN."""
    from triton_distributed_tpu.models.kv_cache import init_kv_cache

    cfg, params = tiny_model
    eng = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                 page_size=4, kv_dtype=E8)
    lin = init_kv_cache(cfg, 1, 64)
    lin = lin._replace(k=lin.k.at[0, 0, 0, 0, 0].set(1e4),
                       offset=jnp.int32(8))
    paged = eng.to_paged(lin)
    kp = np.asarray(paged.k_pools.astype(jnp.float32))
    assert paged.k_pools.dtype == E8
    assert np.isfinite(kp).all() and kp.max() == 448.0


# ---------------------------------------------------------------------------
# Serving tier: parity + gauge.
# ---------------------------------------------------------------------------

def test_fp8kv_serving_matches_sequential_quantized_serve(tiny_model,
                                                          ctx1):
    """Continuous batching over e4m3 pools is token-identical to the
    sequential QUANTIZED serve (Engine.serve with the same kv_dtype) —
    including a request preempted under page pressure and resumed by
    recompute ON the fp8 pool."""
    cfg, params = tiny_model
    rng = np.random.default_rng(2)
    reqs_in = [(rng.integers(0, cfg.vocab_size, n).tolist(), g)
               for n, g in ((8, 6), (10, 5), (6, 4))]
    eng = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                 page_size=4, kv_dtype=E8)
    se = ServingEngine(eng, max_batch=2, num_pages=6, prefill_chunk=4)
    reqs = []
    for i, (p, g) in enumerate(reqs_in):
        req, res = se.submit(p, g, req_id=f"f8-{i}")
        assert res.name == "ADMITTED", res
        reqs.append(req)
    se.run()
    oracle = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4, kv_dtype=E8)
    for i, (p, g) in enumerate(reqs_in):
        gold = np.asarray(oracle.serve(jnp.asarray([p], jnp.int32), g)
                          )[0].tolist()
        assert reqs[i].tokens == gold, (i, reqs[i].tokens, gold)
    assert sum(r.preemptions for r in reqs) > 0, \
        "pool sizing no longer exercises preemption on the fp8 pool"


def test_kv_pages_resident_gauge_published(tiny_model, ctx1, tmp_path):
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import metrics as obs_metrics

    cfg, params = tiny_model
    eng = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                 page_size=4, kv_dtype=E8)
    obs.start_run(str(tmp_path / "run"))
    try:
        se = ServingEngine(eng, max_batch=2, num_pages=6,
                           prefill_chunk=4)
        se.submit(list(range(2, 8)), 2, req_id="g0")
        se.run()
        snap = obs_metrics.registry().snapshot()
    finally:
        obs.finish_run()
    g = snap.get(obs_metrics.KV_PAGES_RESIDENT)
    assert g is not None and g["value"] == se.num_pages


# ---------------------------------------------------------------------------
# Drift vs full-width KV (teacher-forced, 64 steps).
# ---------------------------------------------------------------------------

def test_fp8kv_drift_bound_over_64_steps(tiny_model):
    """Teacher-forced drift bound: over 64 decode steps on a random
    stream, the e4m3-pool logits stay within 20% relative of the
    full-width logits and the per-step argmax agrees >= 75% of the time
    (measured ~7.7% / ~92% with margin — a REGRESSION here means the
    quantization error model changed, e.g. a lost clamp or a double
    quantization)."""
    from triton_distributed_tpu.models.dense import dense_decode_step_paged

    cfg, params = tiny_model
    rng = np.random.default_rng(3)
    steps = 64
    stream = rng.integers(0, cfg.vocab_size, steps)

    @functools.partial(jax.jit, donate_argnums=(1,))
    def step(tok, cache):
        return dense_decode_step_paged(params, cfg, tok, cache,
                                       num_ranks=1, mode="ar")

    def run(kv_dtype):
        cache = init_paged_model_cache(cfg, 1, page_size=4, max_pages=24,
                                       kv_dtype=kv_dtype)
        out = []
        for t in range(steps):
            logits, cache = step(jnp.asarray([stream[t]], jnp.int32),
                                 cache)
            out.append(np.asarray(logits)[0])
        return np.stack(out)

    full, f8 = run(None), run(E8)
    rel = (np.linalg.norm(f8 - full, axis=1)
           / np.linalg.norm(full, axis=1))
    agree = (full.argmax(1) == f8.argmax(1)).mean()
    assert rel.max() < 0.20, f"logits drift {rel.max():.3f} out of bound"
    assert agree >= 0.75, f"argmax agreement {agree:.2f} out of bound"


# ---------------------------------------------------------------------------
# Megakernel paged lane (ATTN_DECODE_PAGED_F8 / APPEND_KV_F8).
# ---------------------------------------------------------------------------

def test_megakernel_fp8kv_serving_matches_quantized_xla(mk_model, ctx1):
    """ServingEngine(backend='megakernel') over fp8 pools serves
    token-identical to the sequential quantized xla serve, including a
    preempt/resume round-trip, with the fp8 lane ACTIVE the whole way
    (no silent demotion) — the cross-backend half of the acceptance
    criteria."""
    cfg, params = mk_model
    rng = np.random.default_rng(9)
    # One LONG generation: each decode step attends the PREVIOUS steps'
    # appended KV, so a current-token quantization mismatch between the
    # in-kernel fold and the dense append compounds within a few steps
    # (review r12: the unquantized c0/d0 fold diverged by step ~6 — a
    # short-generation test passes on seed luck).
    reqs_in = [(rng.integers(0, 512, 126).tolist(), 25, 1),
               (rng.integers(0, 512, 100).tolist(), 4, 0)]
    eng = Engine(cfg, params, ctx1, backend="megakernel", max_seq=256,
                 page_size=128, kv_dtype=E8)
    se = ServingEngine(eng, max_batch=2, num_pages=2, prefill_chunk=128)
    assert se._mk is not None and se._mk.kv_fp8, \
        "fp8 megakernel lane not active"
    reqs = []
    for i, (p, g, prio) in enumerate(reqs_in):
        req, res = se.submit(p, g, priority=prio, req_id=f"mkf8-{i}")
        assert res.name == "ADMITTED", res
        reqs.append(req)
    se.run()
    assert eng.backend == "megakernel" and se._mk is not None
    oracle = Engine(cfg, params, ctx1, backend="xla", max_seq=256,
                    page_size=128, kv_dtype=E8)
    for i, (p, g, _pr) in enumerate(reqs_in):
        gold = np.asarray(oracle.serve(jnp.asarray([p], jnp.int32), g)
                          )[0].tolist()
        assert reqs[i].tokens == gold, (i, reqs[i].tokens, gold)
    assert any(r.preemptions > 0 for r in reqs), \
        "pool sizing no longer exercises preemption on the fp8 MK lane"


def test_megakernel_fp8kv_named_errors(mk_model, ctx1, monkeypatch):
    """The fp8-KV combo surface is NAMED, not silently excluded: the
    build form rejects kv_fp8 outside the serving pool form and with
    tiled fp8 weights; an unservable kv_dtype demotes through the
    ladder (or propagates named with the ladder pinned)."""
    from triton_distributed_tpu.megakernel.models import build_decode_step
    from triton_distributed_tpu.megakernel.serving import (
        PagedMegakernelDecoder,
    )

    cfg, params = mk_model
    kw = dict(hidden=256, hq_local=2, hkv_local=1, ffn_local=256,
              num_layers=1, max_seq=256, pos=255)
    with pytest.raises(ValueError, match="SERVING pool form"):
        build_decode_step(**kw, kv_fp8=True)
    with pytest.raises(ValueError, match="fp8_weights"):
        build_decode_step(**kw, kv_fp8=True, paged=True,
                          inkernel_append=True, fp8_weights=True,
                          kv_pool_pages=3, table_pages=2)
    with pytest.raises(ValueError, match="kv_dtype"):
        PagedMegakernelDecoder(cfg, params, num_slots=1, num_pages=2,
                               max_pages=2, dtype=jnp.float32,
                               kv_dtype=jnp.bfloat16)
    # Through the serving tier: demote, don't die...
    eng = Engine(cfg, params, ctx1, backend="megakernel", max_seq=256,
                 page_size=128, kv_dtype=jnp.bfloat16)
    se = ServingEngine(eng, max_batch=1, num_pages=2, prefill_chunk=128)
    assert se._mk is None and eng.backend != "megakernel"
    # ...unless the operator pinned the ladder: then the named error.
    monkeypatch.setenv("TDTPU_DEMOTION_LADDER", "0")
    from triton_distributed_tpu.resilience import BackendUnsupportedError

    eng2 = Engine(cfg, params, ctx1, backend="megakernel", max_seq=256,
                  page_size=128, kv_dtype=jnp.bfloat16)
    with pytest.raises(BackendUnsupportedError, match="kv_dtype"):
        ServingEngine(eng2, max_batch=1, num_pages=2, prefill_chunk=128)


def test_builder_kv8_space_guards():
    """kv8 pool handles are paged-attention/append operands ONLY (their
    tile ids alias main-workspace ids), and an append must never mix
    pool spaces."""
    from triton_distributed_tpu.megakernel.builder import MegaKernelBuilder
    from triton_distributed_tpu.megakernel.tasks import TILE

    mb = MegaKernelBuilder()
    x = mb.tensor(TILE, TILE)
    pool = mb.tensor(TILE, TILE, kv8=True)
    with pytest.raises(ValueError, match="kv8"):
        mb.add(x, pool, x)
    with pytest.raises(ValueError, match="ONE space"):
        mb.append_kv(pool, mb.tensor(TILE, TILE), 0, x, x)
    with pytest.raises(ValueError, match="kv8"):
        mb.compile().split_feeds({pool: np.zeros((TILE, TILE))})
    with pytest.raises(ValueError, match="fp8.*kv8|kv8.*fp8"):
        mb.tensor(TILE, TILE, fp8=True, kv8=True)
