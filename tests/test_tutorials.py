"""Bit-rot guard: tutorials are user-facing entry points and must keep
running. Each executes in a fresh process (they pin their own CPU mesh).

Only a representative subset runs here — the full set (01-10) is exercised
manually / by CI-style sweeps; each costs a fresh 8-device interpret-mode
startup, so running all of them would dominate suite time.
"""

import os
import subprocess
import sys

import pytest

_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "tutorials")


@pytest.mark.parametrize("script", [
    "01-distributed-notify-wait.py",     # primitives
    "07-overlapping-allgather-gemm.py",  # the flagship overlap pattern
    "04-moe-infer-all2all.py",           # MoE AllToAll
    "12-barrier-free-decode-streams.py", # parity-stream decode collectives
])
def test_tutorial_runs(script):
    env = dict(os.environ)
    env.pop("TDTPU_TUTORIALS_ON_TPU", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(_DIR, script)],
        capture_output=True, text=True, timeout=600, env=env, cwd=_DIR)
    assert proc.returncode == 0, (
        f"{script} failed\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}")
    assert "OK" in proc.stdout