"""Bit-rot guard: tutorials are user-facing entry points and must keep
running. Each executes in a fresh process (they pin their own CPU mesh).

Two tiers (VERDICT r3 #9 — the skipped tutorials 09-11 exercised exactly
the subsystems that churn):
- the fast representative 4 run in the default suite;
- ALL 12 run under ``-m tutorials`` (each costs a fresh 8-device
  interpret-mode startup, so the full sweep is marked for nightly-style
  runs: ``pytest -m tutorials tests/test_tutorials.py``).
"""

import os
import subprocess
import sys

import pytest

_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "tutorials")

_FAST = [
    "01-distributed-notify-wait.py",     # primitives
    "07-overlapping-allgather-gemm.py",  # the flagship overlap pattern
    "04-moe-infer-all2all.py",           # MoE AllToAll
    "12-barrier-free-decode-streams.py", # parity-stream decode collectives
]

_ALL = sorted(f for f in os.listdir(_DIR)
              if f[:2].isdigit() and f.endswith(".py"))


def _run(script):
    env = dict(os.environ)
    env.pop("TDTPU_TUTORIALS_ON_TPU", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(_DIR, script)],
        capture_output=True, text=True, timeout=900, env=env, cwd=_DIR)
    assert proc.returncode == 0, (
        f"{script} failed\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-2000:]}")
    assert "OK" in proc.stdout


@pytest.mark.parametrize("script", _FAST)
def test_tutorial_runs(script):
    _run(script)


@pytest.mark.tutorials
@pytest.mark.slow
@pytest.mark.parametrize("script", [s for s in _ALL if s not in _FAST])
def test_tutorial_runs_full_sweep(script):
    """The remaining 8 tutorials — nightly tier (`pytest -m tutorials`).

    Also marked ``slow``: a ``-m`` on the command line *replaces* the
    addopts-level ``-m 'not tutorials'``, so without this the tier-1
    sweep (``-m 'not slow'``) would silently run all 12 fresh-process
    tutorials."""
    _run(script)


def test_all_tutorials_enumerated():
    """The sweep must cover every numbered tutorial on disk (a new
    tutorial without a guard would silently rot)."""
    assert len(_ALL) == 12, _ALL
    assert set(_FAST) <= set(_ALL)
