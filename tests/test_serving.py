"""Continuous-batching serving tier (ISSUE 7, docs/serving.md).

The load-bearing contract: iteration-level scheduling over the shared
paged pool must be TOKEN-IDENTICAL per request to sequential
``Engine.serve`` calls (greedy) — including a request preempted under
page pressure and resumed by recompute — while admission backpressure
and the SLO-driven admission width behave deterministically.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.models.config import tiny_config
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.kv_cache import (
    PageAllocator, PageBudgetError, PagePoolConfigError,
    init_paged_model_cache,
)
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving import (
    AdmitResult, Request, RequestState, RequestTooLargeError,
    ServingConfigError, ServingEngine,
)
from triton_distributed_tpu.serving.scheduler import Scheduler


@pytest.fixture(scope="module")
def ctx1():
    return initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])


@pytest.fixture(scope="module")
def served(ctx1):
    """(engine, params) — one tiny paged engine shared by the loop
    tests (jit caches warm across them)."""
    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(7), cfg)
    eng = Engine(cfg, params, ctx1, backend="xla", max_seq=64, page_size=4)
    return eng


def _prompts(seed, n, lengths=(6, 9), vocab=256):
    """Random prompts drawn from a SMALL set of lengths: every distinct
    length costs the golden sequential serve one prefill compile."""
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, int(rng.choice(lengths))).tolist()
            for _ in range(n)]


# ---------------------------------------------------------------------------
# Page allocator (satellite: extracted, tested, named errors).
# ---------------------------------------------------------------------------

def test_page_allocator_alloc_free_budget():
    al = PageAllocator(6, 3)
    a = al.alloc_pages("a", 2)
    assert a == [0, 1] and al.free_count == 4
    b = al.alloc_pages("b", 3)
    assert b == [2, 3, 4] and al.pages("b") == [2, 3, 4]
    # Per-sequence budget: a named error, not pool exhaustion.
    with pytest.raises(PageBudgetError, match="max_pages budget of 3"):
        al.alloc_pages("a", 2)
    # Pool exhaustion: None (the scheduler preempts), never an exception.
    assert al.alloc_pages("a", 1) == [5]
    assert al.alloc_pages("b", 0) == []
    assert al.free_count == 0
    al2 = PageAllocator(4, 4)
    al2.alloc_pages("x", 4)
    assert al2.alloc_pages("y", 1) is None
    # Freeing returns pages lowest-first again (deterministic replay).
    assert al.free_pages("a") == 3
    assert al.alloc_pages("c", 1) == [0]
    assert al.free_pages("nobody") == 0    # double-free is a no-op


def test_page_allocator_reserved_and_for_cache():
    cfg = tiny_config()
    cache = init_paged_model_cache(cfg, 2, page_size=4, max_pages=4,
                                   num_pages=9)
    al = PageAllocator.for_cache(cache, reserved=(8,))
    assert al.free_count == 8
    got = [al.alloc_pages(f"r{i}", 1)[0] for i in range(8)]
    assert 8 not in got                    # the scratch page stays out


def test_paged_pool_config_validation():
    cfg = tiny_config()
    with pytest.raises(PagePoolConfigError, match="field page_size"):
        init_paged_model_cache(cfg, 1, page_size=0, max_pages=4)
    with pytest.raises(PagePoolConfigError, match="field max_pages"):
        init_paged_model_cache(cfg, 1, page_size=4, max_pages=0)
    with pytest.raises(PagePoolConfigError, match="field num_pages"):
        init_paged_model_cache(cfg, 1, page_size=4, max_pages=4,
                               num_pages=-1)


# ---------------------------------------------------------------------------
# Request lifecycle.
# ---------------------------------------------------------------------------

def test_request_lifecycle_and_accounting():
    r = Request(prompt=[1, 2, 3, 4, 5], max_new_tokens=4)
    assert r.state is RequestState.WAITING
    r.advance(RequestState.PREFILLING)
    r.advance(RequestState.RUNNING)
    r.advance(RequestState.PREEMPTED)
    with pytest.raises(ValueError, match="illegal request transition"):
        r.advance(RequestState.RUNNING)    # must re-prefill first
    r.advance(RequestState.PREFILLING)
    r.advance(RequestState.FINISHED)
    # Accounting view: final KV excludes the last generated token.
    assert r.final_kv_len == 5 + 4 - 1
    assert r.page_budget(page_size=4) == 2
    r.kv_len = 7
    assert r.pages_needed(4, extra=1) == 2
    with pytest.raises(ValueError, match="max_new_tokens"):
        Request(prompt=[1], max_new_tokens=0)


# ---------------------------------------------------------------------------
# Scheduler admission / backpressure / preemption (pure host logic).
# ---------------------------------------------------------------------------

def _sched(num_slots=2, num_pages=8, max_pages=4, page=4, max_waiting=2):
    return Scheduler(num_slots=num_slots,
                     allocator=PageAllocator(num_pages, max_pages),
                     page_size=page, capacity_tokens=max_pages * page,
                     max_waiting=max_waiting)


def test_admit_backpressure_queue_and_pool():
    s = _sched(max_waiting=2)
    assert s.admit(Request(prompt=[1] * 4, max_new_tokens=2),
                   0.0) is AdmitResult.ADMITTED
    assert s.admit(Request(prompt=[1] * 4, max_new_tokens=2),
                   0.0) is AdmitResult.ADMITTED
    assert s.admit(Request(prompt=[1] * 4, max_new_tokens=2),
                   0.0) is AdmitResult.QUEUE_FULL     # queue bound
    s2 = _sched(max_waiting=8)
    s2.allocator.alloc_pages("hog", 4)
    s2.allocator.alloc_pages("hog2", 4)
    assert s2.allocator.free_count == 0
    assert s2.admit(Request(prompt=[1] * 4, max_new_tokens=2),
                    0.0) is AdmitResult.QUEUE_FULL    # pool exhausted


def test_admit_rejects_unservable_request():
    s = _sched()
    with pytest.raises(RequestTooLargeError, match="per-sequence"):
        s.admit(Request(prompt=[1] * 20, max_new_tokens=8), 0.0)
    s3 = Scheduler(num_slots=1, allocator=PageAllocator(2, 4),
                   page_size=4, capacity_tokens=16, max_waiting=4)
    with pytest.raises(RequestTooLargeError, match="whole pool"):
        s3.admit(Request(prompt=[1] * 10, max_new_tokens=4), 0.0)


def test_scheduler_preempts_lowest_priority_youngest():
    s = _sched(num_slots=3, num_pages=6, max_pages=4, max_waiting=8)
    reqs = [Request(prompt=[1] * 8, max_new_tokens=8, priority=p)
            for p in (1, 0, 0)]
    for r in reqs:
        assert s.admit(r, 0.0) is AdmitResult.ADMITTED
    admitted = s.schedule_admissions()
    assert len(admitted) == 3 and s.allocator.free_count == 0
    for r in reqs:                         # pretend prefill completed
        r.advance(RequestState.RUNNING)
        r.kv_len = 8
    ready, preempted = s.ensure_decode_pages()
    # Every running sequence needs page 3 of its budget; the pool is
    # dry, so the LOWEST-priority YOUNGEST (reqs[2]) goes first.
    assert preempted and preempted[0] is reqs[2]
    assert reqs[2].state is RequestState.PREEMPTED
    assert reqs[2].preemptions == 1 and reqs[2] in s.waiting
    assert reqs[0] in ready                # priority 1 survives
    assert all(r is not reqs[2] for r in ready)


def test_admission_width_shrink_grow():
    s = _sched(num_slots=4)
    assert s.admit_cap == 4
    assert s.shrink_admission() == 3
    assert s.shrink_admission() == 2
    for _ in range(5):
        s.shrink_admission()
    assert s.admit_cap == 1                # floor: never fully closed
    assert s.grow_admission() == 2
    for _ in range(8):
        s.grow_admission()
    assert s.admit_cap == 4                # ceiling: num_slots


# ---------------------------------------------------------------------------
# The serving loop — parity, preemption, SLO coupling, metrics.
# ---------------------------------------------------------------------------

def _serve_all(se, prompts, gens, priorities=None):
    reqs = []
    for i, (p, g) in enumerate(zip(prompts, gens)):
        pr = priorities[i] if priorities else 0
        req, res = se.submit(p, g, priority=pr)
        assert res is AdmitResult.ADMITTED
        reqs.append(req)
    se.run(max_iters=2000)
    return reqs


def _golden(engine, prompts, gens):
    return [np.asarray(engine.serve(jnp.asarray([p], jnp.int32),
                                    gen_len=g))[0].tolist()
            for p, g in zip(prompts, gens)]


def test_serving_parity_vs_sequential(served):
    """4 heterogeneous requests through 2 slots (so admission queues and
    slices interleave with decode) — token-identical to sequential
    serves."""
    se = ServingEngine(served, max_batch=2, prefill_chunk=4)
    prompts = _prompts(0, 4)
    gens = [5, 3, 7, 4]
    reqs = _serve_all(se, prompts, gens)
    assert all(r.state is RequestState.FINISHED for r in reqs)
    assert all(r.t_first_token is not None and r.t_finish is not None
               for r in reqs)
    for r, exp in zip(reqs, _golden(served, prompts, gens)):
        assert r.tokens == exp, f"{r.req_id} diverged"


def test_serving_preempt_resume_parity(served):
    """A pool far smaller than the aggregate demand forces eviction
    mid-decode; the preempted request recomputes on resume and must
    still match its sequential tokens."""
    se = ServingEngine(served, max_batch=3, num_pages=7, prefill_chunk=4)
    prompts = _prompts(3, 5, lengths=(8, 12))
    gens = [8, 6, 8, 6, 7]
    reqs = _serve_all(se, prompts, gens)
    assert all(r.state is RequestState.FINISHED for r in reqs)
    assert sum(r.preemptions for r in reqs) >= 1, \
        "pool sizing no longer forces a preemption"
    for r, exp in zip(reqs, _golden(served, prompts, gens)):
        assert r.tokens == exp, \
            f"{r.req_id} diverged (preemptions={r.preemptions})"


def test_serving_priority_shields_victim(served):
    """Under pressure the high-priority request is never the victim."""
    se = ServingEngine(served, max_batch=2, num_pages=5, prefill_chunk=4)
    prompts = [[1, 2, 3, 4, 5, 6], [7, 8, 9, 10, 11, 12]]
    reqs = _serve_all(se, prompts, [8, 8], priorities=[1, 0])
    assert reqs[0].preemptions == 0
    assert reqs[1].preemptions >= 1
    for r, exp in zip(reqs, _golden(served, prompts, [8, 8])):
        assert r.tokens == exp


def test_serving_config_errors(served, ctx1):
    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(7), cfg)
    unpaged = Engine(cfg, params, ctx1, backend="xla", max_seq=32)
    with pytest.raises(ServingConfigError, match="page_size"):
        ServingEngine(unpaged)
    with pytest.raises(ServingConfigError, match="prefill_chunk"):
        ServingEngine(served, prefill_chunk=6)   # not a page multiple
    with pytest.raises(ServingConfigError, match="max_batch"):
        ServingEngine(served, max_batch=0)
    with pytest.raises(RequestTooLargeError):
        se = ServingEngine(served, max_batch=1)
        se.submit(list(range(60)), 30)           # > capacity


def test_slo_streak_shrinks_then_regrows(served, monkeypatch):
    """An impossible tokens/s floor shrinks the admitted width within
    the shrink budget; clearing it regrows the width on clean streaks
    (acceptance criterion c)."""
    from triton_distributed_tpu.obs.slo import SLOConfig

    monkeypatch.setenv("TDTPU_ADMIT_SHRINK_AFTER", "2")
    monkeypatch.setenv("TDTPU_ADMIT_GROW_AFTER", "3")
    se = ServingEngine(served, max_batch=3, prefill_chunk=4,
                       slo_cfg=SLOConfig(tokens_per_s_min=1e12))
    _serve_all(se, _prompts(5, 3), [6, 6, 6])
    assert se.sched.admit_cap < 3
    shrunk = se.sched.admit_cap
    se.slo_cfg = SLOConfig()               # thresholds cleared: clean
    _serve_all(se, _prompts(6, 2), [6, 6])
    assert se.sched.admit_cap > shrunk


def test_serving_metrics_and_report_lane(served, tmp_path):
    """Under an obs run the loop publishes the serving series (TTFT /
    TPOT histograms, queue/pages gauges, preemption counter, ROLLING
    tokens/s gauge) and obs.report renders + gates the lane."""
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import report as obs_report

    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir)
    try:
        se = ServingEngine(served, max_batch=2, num_pages=5,
                           prefill_chunk=4)
        prompts = [[1, 2, 3, 4, 5, 6], [7, 8, 9, 10, 11, 12]]
        _serve_all(se, prompts, [8, 8])
        reg = obs_metrics.registry()
        assert reg.get(obs_metrics.SERVE_TTFT_MS).count == 2
        assert reg.get(obs_metrics.SERVE_TPOT_MS).count == 2
        assert reg.get(obs_metrics.SERVE_FINISHED).value == 2
        assert reg.get(obs_metrics.SERVE_PREEMPTIONS).value >= 1
        assert reg.get(obs_metrics.SERVE_TOKENS_PER_S).value > 0
        assert reg.get(obs_metrics.SERVE_ADMIT_CAP).value == 2
    finally:
        obs.finish_run()
    # Report renders the serving lane; preemptions under a clean SLO
    # section fail --check unless explicitly allowed (the satellite's
    # contract: eviction with no pressure signal = mis-sized pool).
    rc = obs_report.main([run_dir, "--check", "--require-series",
                          obs_metrics.SERVE_TTFT_MS])
    assert rc == 1
    rc = obs_report.main([run_dir, "--check", "--allow-preemptions",
                          "--require-series", obs_metrics.SERVE_TTFT_MS])
    assert rc == 0


def test_backend_demotion_invalidates_serving_jits(ctx1):
    """When the ladder demotes the engine backend, this tier's
    slice/logits jits (built under the OLD backend's mode) must drop —
    a demoted engine must not keep prefilling through the collective
    stack the demotion routed around. Output stays token-identical
    (the ladder's contract)."""
    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(7), cfg)
    eng = Engine(cfg, params, ctx1, backend="auto", max_seq=64,
                 page_size=4)
    assert eng._ladder == ["auto", "xla"]
    se = ServingEngine(eng, max_batch=1, prefill_chunk=4)
    prompt = [5, 4, 3, 2, 1, 6]
    req1, _ = se.submit(prompt, 4)
    se.run()
    assert "pf_slice" in se._jits and se._jits_backend == "auto"
    eng._set_rung(1, "test demotion")          # auto -> xla
    req2, _ = se.submit(prompt, 4)
    se.run()
    assert se._jits_backend == "xla"           # caches were rebuilt
    assert req2.tokens == req1.tokens          # ladder parity holds


def test_rolling_rate_window(served):
    """The tokens/s gauge is a trailing-window rate, not a per-call
    number (ISSUE 7 satellite): events outside the window fall out."""
    t = [0.0]
    se = ServingEngine(served, max_batch=1, clock=lambda: t[0])
    se._t0 = 0.0
    se._rate_window_s = 5.0
    se._rate_events.extend([(0.0, 10), (1.0, 10)])
    t[0] = 2.0
    assert se._rolling_rate() == pytest.approx(10.0)   # 20 tok / 2 s
    t[0] = 5.5
    assert se._rolling_rate() == pytest.approx(2.0)    # 10 tok / 5 s
    t[0] = 60.0
    assert se._rolling_rate() == 0.0


def test_loadgen_trace_determinism():
    """Seeded traces are bit-reproducible — the serving runs they drive
    replay identically."""
    from triton_distributed_tpu.serving.loadgen import LoadSpec, build_trace

    t1 = build_trace(LoadSpec(seed=3))
    t2 = build_trace(LoadSpec(seed=3))
    assert t1 == t2
    assert t1 != build_trace(LoadSpec(seed=4))


@pytest.mark.slow
def test_loadgen_dryrun(tmp_path):
    """The full dryrun (parity incl. preempt/resume, backpressure, SLO
    shrink) — slow tier: CI runs the same proof as its own serving
    smoke step (`loadgen --dryrun`), so tier-1 need not pay it twice."""
    import json

    from triton_distributed_tpu.serving.loadgen import dryrun

    out = str(tmp_path / "serving-report.json")
    assert dryrun(out) == 0
    rep = json.load(open(out))
    assert rep["all_finished"] and rep["parity_ok"]
    assert rep["preempted_with_parity"]
    assert rep["backpressure_fired"]
    assert rep["slo_admission"]["shrunk"]
