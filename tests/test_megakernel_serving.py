"""Megakernel serving glue: real model params through the persistent-kernel
decode loop, token-identical to the jitted ar decode path (reference
mega_triton_kernel/models/qwen3.py + model_server.py — VERDICT r2 #5)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.models.config import ModelConfig
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.runtime import initialize_distributed


@pytest.fixture(scope="module")
def ctx1():
    """Single-device mesh (the megakernel serving view)."""
    return initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])


@pytest.fixture(scope="module")
def tiny_model():
    # head_dim must equal TILE (128) for the megakernel attention task.
    cfg = ModelConfig(hidden_size=256, intermediate_size=256, num_layers=2,
                      num_heads=2, num_kv_heads=1, head_dim=128,
                      vocab_size=512, qk_norm=True, dtype="float32")
    params = init_dense_llm(jax.random.PRNGKey(0), cfg)
    return cfg, params


def test_megakernel_serve_matches_ar(ctx1, tiny_model):
    cfg, params = tiny_model
    ids = np.array([[3, 141, 59, 26, 5]], np.int32)
    gen = 6

    eng_ar = Engine(cfg, params, ctx1, backend="auto", max_seq=128)
    out_ar = np.asarray(eng_ar.serve(jnp.asarray(ids), gen_len=gen))

    eng_mk = Engine(cfg, params, ctx1, backend="megakernel", max_seq=128)
    out_mk = np.asarray(eng_mk.serve(jnp.asarray(ids), gen_len=gen))

    assert out_ar.shape == out_mk.shape == (1, gen)
    np.testing.assert_array_equal(out_ar, out_mk)


def test_megakernel_decoder_validates(ctx1, tiny_model):
    from triton_distributed_tpu.megakernel.serving import (
        validate_megakernel_cfg,
    )

    cfg, _ = tiny_model
    validate_megakernel_cfg(cfg, 128)
    # Round 9: head_dim 64 is SERVED (padded-head layout) — only other
    # head dims stay rejected.
    validate_megakernel_cfg(
        ModelConfig(head_dim=64, hidden_size=256,
                    intermediate_size=256), 128)
    with pytest.raises(ValueError, match="head_dim"):
        validate_megakernel_cfg(
            ModelConfig(head_dim=96, hidden_size=256,
                        intermediate_size=256), 128)
    with pytest.raises(ValueError, match="TILE multiple"):
        validate_megakernel_cfg(cfg, 100)


def test_megakernel_serve_tp8_matches_ar(ctx):
    """TP=8 megakernel serving on the CPU mesh: per-rank weight/cache
    shards feed the workspace, the decode step runs under shard_map with
    in-kernel AllReduce tasks, and generation is token-identical to the
    jitted ar backend (the reference's multi-GPU MegaTritonKernel serving
    shape — previously only exercised at kernel level)."""
    cfg = ModelConfig(hidden_size=256, intermediate_size=1024, num_layers=1,
                      num_heads=8, num_kv_heads=8, head_dim=128,
                      vocab_size=256, qk_norm=True, dtype="float32")
    params = init_dense_llm(jax.random.PRNGKey(1), cfg)
    ids = np.array([[7, 101, 33]], np.int32)
    gen = 4

    eng_ar = Engine(cfg, params, ctx, backend="auto", max_seq=128)
    out_ar = np.asarray(eng_ar.serve(jnp.asarray(ids), gen_len=gen))

    eng_mk = Engine(cfg, params, ctx, backend="megakernel", max_seq=128)
    out_mk = np.asarray(eng_mk.serve(jnp.asarray(ids), gen_len=gen))

    np.testing.assert_array_equal(out_ar, out_mk)


def test_megakernel_fp8_weights_matches_quantized_golden(ctx1, tiny_model):
    """fp8_weights serving == the ar path run on e4m3-quantized weights:
    the fp8 weight workspace must change ONLY the weight quantization, not
    the transport/compute semantics."""
    import jax.tree_util as jtu

    from triton_distributed_tpu.megakernel.serving import MegakernelDecoder
    from triton_distributed_tpu.models.dense import dense_prefill
    from triton_distributed_tpu.models.kv_cache import init_kv_cache

    cfg, params = tiny_model
    ids = np.array([[3, 141, 59, 26, 5]], np.int32)
    gen = 5

    def quant(path, x):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
            return jnp.asarray(x).astype(jnp.float8_e4m3fn).astype(x.dtype)
        return x

    params_q = jtu.tree_map_with_path(quant, params)

    # Golden: ar Engine on pre-quantized weights.
    eng_q = Engine(cfg, params_q, ctx1, backend="ar", max_seq=128)
    out_q = np.asarray(eng_q.serve(jnp.asarray(ids), gen_len=gen))

    # fp8 megakernel: full-precision params in, e4m3 workspace inside.
    dec = MegakernelDecoder(cfg, params, max_seq=128, ctx=ctx1,
                            num_ranks=1, fp8_weights=True)
    cache = init_kv_cache(cfg, 1, 128, dtype=jnp.float32)
    # Prefill must also see the quantized weights for token identity.
    logits, cache = dense_prefill(params_q, cfg, jnp.asarray(ids), cache,
                                  num_ranks=1)
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    ws = dec.start(cache)
    toks = [int(tok[0])]
    pos = int(cache.offset)
    for _ in range(gen - 1):
        ws, tok = dec.step(ws, tok, pos)
        toks.append(int(tok[0]))
        pos += 1
    np.testing.assert_array_equal(np.asarray([toks]), out_q)
