"""Round-9 megakernel serving lane: paged workspace + shape
generalization + ladder-integrated demotion.

Covers the ISSUE-9 acceptance set on CPU interpret mode:

* paged-megakernel decode token-parity vs ``dense_decode_step_paged``
  over heterogeneous ``kv_lens`` (each slot its own page table over the
  shared pools);
* ``ServingEngine(backend="megakernel")`` token-identical to the xla
  serving loop, including a preempted+resumed request ON the paged
  workspace (the loadgen dryrun repeats this contract in CI);
* head_dim-64 (padded-head layout) and batch = 2·TILE (row-blocked
  emission) parity vs the chained golden;
* ``BackendUnsupportedError`` demotes through the PR-6 ladder instead
  of killing serve (page-shape mismatch = transient);
* the PageAllocator accounts the megakernel scratch page under
  ``reserved=`` (budget math can't oversubscribe the pool).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.megakernel.tasks import TILE
from triton_distributed_tpu.models.config import ModelConfig
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving.loop import ServingEngine


@pytest.fixture(scope="module")
def ctx1():
    return initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])


@pytest.fixture(scope="module")
def tiny_model():
    cfg = ModelConfig(hidden_size=256, intermediate_size=256, num_layers=2,
                      num_heads=2, num_kv_heads=1, head_dim=128,
                      vocab_size=512, qk_norm=True, dtype="float32")
    params = init_dense_llm(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def one_layer_model():
    cfg = ModelConfig(hidden_size=256, intermediate_size=256, num_layers=1,
                      num_heads=2, num_kv_heads=1, head_dim=128,
                      vocab_size=512, qk_norm=True, dtype="float32")
    params = init_dense_llm(jax.random.PRNGKey(1), cfg)
    return cfg, params


def test_paged_megakernel_decode_parity_heterogeneous(tiny_model):
    """Paged MK decode == dense_decode_step_paged token-for-token over
    two slots at different lengths (own pages each, in-kernel appends
    advancing the pools)."""
    from triton_distributed_tpu.megakernel.serving import (
        PagedMegakernelDecoder,
    )
    from triton_distributed_tpu.models import sampling
    from triton_distributed_tpu.models.dense import (
        dense_decode_step_paged, dense_prefill,
    )
    from triton_distributed_tpu.models.kv_cache import (
        init_kv_cache, init_paged_model_cache,
    )

    cfg, params = tiny_model
    prompts = [[3, 141, 59, 26, 5], [7, 9, 23]]
    num_slots, num_pages, max_pages = 2, 4, 2
    dec = PagedMegakernelDecoder(cfg, params, num_slots=num_slots,
                                 num_pages=num_pages, max_pages=max_pages)
    ws = dec.start()

    pcache = init_paged_model_cache(cfg, num_slots, page_size=TILE,
                                    max_pages=max_pages,
                                    num_pages=num_pages + 1)
    table = np.full((num_slots, max_pages), num_pages, np.int32)
    page_alloc = {0: [0, 1], 1: [2, 3]}
    kv_lens = np.zeros(num_slots, np.int32)
    toks = np.zeros(num_slots, np.int32)
    kp = np.array(pcache.k_pools)
    vp = np.array(pcache.v_pools)
    for b, prompt in enumerate(prompts):
        lin = init_kv_cache(cfg, 1, 256)
        logits, lin = dense_prefill(params, cfg,
                                    jnp.asarray([prompt], jnp.int32), lin,
                                    num_ranks=1)
        toks[b] = int(np.asarray(sampling.greedy(logits))[0])
        kv_lens[b] = len(prompt)
        pages = page_alloc[b]
        table[b, :len(pages)] = pages
        ws = dec.load_prefill(ws, lin.k, lin.v, pages)
        kl, vl = np.asarray(lin.k), np.asarray(lin.v)
        for i, p in enumerate(pages):
            kp[:, p] = kl[:, 0, i * TILE:(i + 1) * TILE]
            vp[:, p] = vl[:, 0, i * TILE:(i + 1) * TILE]
    pcache = pcache._replace(
        k_pools=jnp.asarray(kp), v_pools=jnp.asarray(vp),
        page_table=jnp.asarray(table), kv_lens=jnp.asarray(kv_lens))

    mk_tok = toks.copy()
    g_tok = jnp.asarray(toks)
    for _ in range(3):
        tables = [page_alloc[b] for b in range(num_slots)]
        ws, nt = dec.step(ws, mk_tok, kv_lens, tables)
        mk_tok = np.asarray(nt)
        logits, pcache = dense_decode_step_paged(
            params, cfg, g_tok, pcache, num_ranks=1, mode="xla_rep")
        g_tok = sampling.greedy(logits)
        np.testing.assert_array_equal(mk_tok, np.asarray(g_tok))
        kv_lens = kv_lens + 1

    # The host retarget validates page coverage: a kv_len needing more
    # pages than the table maps must fail loudly (silently riding the
    # scratch page would corrupt the sequence).
    with pytest.raises(ValueError, match="mapped pages"):
        dec._retarget([TILE + 1, 0], [[0], []])
    with pytest.raises(ValueError, match="at capacity"):
        dec._retarget([dec.capacity, 0], [[0, 1], []])
    # Write-side twin: at an exact page boundary the APPEND page (index
    # kvl // TILE) must also be mapped, or the token's KV would silently
    # land on the scratch page.
    with pytest.raises(ValueError, match="page growth"):
        dec._retarget([TILE, 0], [[0], []])


def test_serving_engine_megakernel_matches_xla(tiny_model, ctx1):
    """ServingEngine(backend='megakernel') serves token-identical to the
    xla serving loop — 3 requests through 2 slots (slot reuse), decode
    on the persistent kernel the whole way (no silent demotion)."""
    cfg, params = tiny_model
    reqs = [([3, 141, 59, 26, 5], 4), ([7, 9, 23], 5), ([100, 4], 3)]

    def run(backend):
        eng = Engine(cfg, params, ctx1, backend=backend, max_seq=256,
                     page_size=128)
        se = ServingEngine(eng, max_batch=2, num_pages=4,
                           prefill_chunk=128)
        out = {}
        for i, (p, mn) in enumerate(reqs):
            req, res = se.submit(p, mn, req_id=f"r{i}")
            assert res.name == "ADMITTED", res
            out[req.req_id] = req
        se.run()
        return {k: r.tokens for k, r in out.items()}, se

    mk, se_mk = run("megakernel")
    assert se_mk._mk is not None, "megakernel lane demoted unexpectedly"
    assert se_mk.engine.backend == "megakernel"
    xla, _ = run("xla")
    assert mk == xla


def test_serving_engine_megakernel_preempt_resume(one_layer_model, ctx1):
    """A request preempted under page pressure ON the paged megakernel
    workspace resumes (recompute) and still matches the xla loop —
    the PR-7 admission/preemption machinery drives the persistent
    backend unchanged."""
    cfg, params = one_layer_model
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, 512, 126).tolist(), 6, 1),
            (rng.integers(0, 512, 100).tolist(), 4, 0)]

    def run(backend):
        eng = Engine(cfg, params, ctx1, backend=backend, max_seq=256,
                     page_size=128)
        se = ServingEngine(eng, max_batch=2, num_pages=2,
                           prefill_chunk=128)
        out = {}
        for i, (p, mn, prio) in enumerate(reqs):
            req, res = se.submit(p, mn, priority=prio, req_id=f"r{i}")
            assert res.name == "ADMITTED", res
            out[req.req_id] = req
        se.run()
        return out, se

    mk, se_mk = run("megakernel")
    xla, _ = run("xla")
    assert se_mk._mk is not None
    assert {k: r.tokens for k, r in mk.items()} \
        == {k: r.tokens for k, r in xla.items()}
    assert any(r.preemptions > 0 for r in mk.values()), \
        "pool sizing no longer exercises preemption on the MK lane"


def test_megakernel_backend_demotes_not_dies(tiny_model, ctx1):
    """Workspace/page-shape mismatch = TRANSIENT: (a) ServingEngine with
    page_size != TILE demotes through the ladder at construction and
    still serves; (b) sequential Engine.serve on a paged megakernel
    engine demotes instead of raising the old anonymous ValueError."""
    import warnings

    from triton_distributed_tpu import resilience

    cfg, params = tiny_model
    # (a) serving tier: page 64 mismatches TILE.
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        eng = Engine(cfg, params, ctx1, backend="megakernel", max_seq=256,
                     page_size=64)
        se = ServingEngine(eng, max_batch=2, num_pages=8, prefill_chunk=64)
    assert se._mk is None
    assert eng.backend != "megakernel"
    req, res = se.submit([7, 9, 23], 3, req_id="d0")
    se.run()
    assert len(req.tokens) == 3

    # (b) sequential serve: BackendUnsupportedError is transient and the
    # ladder demotes; the output matches the xla engine token-for-token.
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        eng2 = Engine(cfg, params, ctx1, backend="megakernel",
                      max_seq=256, page_size=64)
        out = np.asarray(eng2.serve(jnp.asarray([[3, 141, 59]], jnp.int32),
                                    gen_len=4))
    assert eng2.backend != "megakernel"
    eng_x = Engine(cfg, params, ctx1, backend="xla", max_seq=256)
    out_x = np.asarray(eng_x.serve(jnp.asarray([[3, 141, 59]], jnp.int32),
                                   gen_len=4))
    np.testing.assert_array_equal(out, out_x)
    assert resilience.is_transient(
        resilience.BackendUnsupportedError("x"))


def test_ladder_disabled_raises_named_error(tiny_model, ctx1, monkeypatch):
    """With TDTPU_DEMOTION_LADDER=0 the mismatch must surface as the
    NAMED BackendUnsupportedError (an operator who pinned the backend
    gets the diagnosis, not a silent fallback)."""
    from triton_distributed_tpu.resilience import BackendUnsupportedError

    cfg, params = tiny_model
    monkeypatch.setenv("TDTPU_DEMOTION_LADDER", "0")
    eng = Engine(cfg, params, ctx1, backend="megakernel", max_seq=256,
                 page_size=64)
    with pytest.raises(BackendUnsupportedError, match="page_size"):
        ServingEngine(eng, max_batch=2, num_pages=8, prefill_chunk=64)


def test_page_allocator_reserved_scratch_budget(tiny_model, ctx1):
    """The megakernel scratch page is a REAL reserved pool row: the
    allocator never hands it out, free_count excludes it, and the
    admission budget checks usable (not raw) pages."""
    import warnings

    from triton_distributed_tpu.models.kv_cache import PageAllocator
    from triton_distributed_tpu.serving.scheduler import (
        RequestTooLargeError,
    )

    alloc = PageAllocator(5, 4, reserved=(4,))
    assert alloc.usable_pages == 4
    assert alloc.free_count == 4
    got = alloc.alloc_pages("a", 4)
    assert got == [0, 1, 2, 3]          # scratch (4) never allocated
    assert alloc.alloc_pages("b", 1) is None   # pool exhausted, not scratch
    alloc.free_pages("a")
    assert alloc.free_count == 4

    # Serving wiring: with the MK lane active the scheduler's allocator
    # carries the scratch page reserved, and a request sized to the RAW
    # pool (num_pages + scratch) is refused up front.
    cfg, params = tiny_model
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        eng = Engine(cfg, params, ctx1, backend="megakernel", max_seq=256,
                     page_size=128)
        se = ServingEngine(eng, max_batch=2, num_pages=1,
                           prefill_chunk=128)
    assert se._mk is not None
    a = se.sched.allocator
    assert a.num_pages == 2 and a.usable_pages == 1
    assert a.reserved == (se.scratch_page,)
    with pytest.raises(RequestTooLargeError, match="usable"):
        # 2 pages of budget vs 1 usable: must be refused at admission.
        se.submit(list(range(100)), 100)


def test_mat_prefetch_warm_program_structure_and_parity():
    """PREFETCH_MAT + gemm_mat(prefetch_first=True): bit-identical to
    the cold task, one PREFETCH_MAT row per warm in the queue, and the
    builder rejects an unconsumed/mismatched warm."""
    from triton_distributed_tpu.megakernel.builder import MegaKernelBuilder
    from triton_distributed_tpu.megakernel.models import build_decode_step
    from triton_distributed_tpu.megakernel.tasks import TaskType

    rng = np.random.default_rng(11)
    mb = MegaKernelBuilder()
    a = mb.tensor(TILE, 256)
    w = mb.tensor_mat(256, 256)
    o_warm = mb.tensor(TILE, 256)
    o_cold = mb.tensor(TILE, 256)
    filler = mb.tensor(TILE, 256)
    fo = mb.tensor(TILE, 256)
    mb.prefetch_mat(w)
    mb.add(fo, filler, filler)       # the task the warm DMA flies under
    mb.gemm_mat(o_warm, a, w, prefetch_first=True)
    mb.gemm_mat(o_cold, a, w)
    comp = mb.compile()
    assert any(sp.warm for sp in comp.mat_specs)
    av = rng.standard_normal((TILE, 256)).astype(np.float32) * 0.1
    wv = rng.standard_normal((256, 256)).astype(np.float32) * 0.1
    fv = rng.standard_normal((TILE, 256)).astype(np.float32)
    r1, r2 = comp.run({a: jnp.asarray(av), w: jnp.asarray(wv),
                       filler: jnp.asarray(fv)},
                      outputs=[o_warm, o_cold])
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))

    # Builder contracts: double warm / mismatched consumer / unconsumed.
    mb2 = MegaKernelBuilder()
    w2 = mb2.tensor_mat(256, 256)
    mb2.prefetch_mat(w2)
    with pytest.raises(ValueError, match="not yet consumed"):
        mb2.prefetch_mat(w2)
    with pytest.raises(ValueError, match="never consumed"):
        mb2.compile()

    # The decode assembly emits one warm per layer at n=1 (the o-proj
    # chunk streaming under attention).
    prog = build_decode_step(hidden=256, hq_local=2, hkv_local=1,
                             ffn_local=256, num_layers=2, max_seq=256,
                             pos=100, num_ranks=1, mat_prefetch=True)
    comp2 = prog.mb.compile()
    q = np.asarray(comp2.queue)[:comp2.num_exec, 0]
    assert (q == int(TaskType.PREFETCH_MAT)).sum() == 2
