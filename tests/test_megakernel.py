"""MegaKernel tests: scheduler, single-device task programs, and the
cross-device AllReduce task (TP MLP block in ONE kernel launch)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.megakernel import (
    MegaKernelBuilder, TensorHandle, topo_schedule, using_native_scheduler,
)
from triton_distributed_tpu.runtime.context import shard_map_on


def test_scheduler_orders_and_detects_cycles():
    order = topo_schedule(4, [(0, 2), (1, 2), (2, 3)])
    assert order.index(2) > max(order.index(0), order.index(1))
    assert order.index(3) > order.index(2)
    with pytest.raises(ValueError, match="cycle"):
        topo_schedule(2, [(0, 1), (1, 0)])


def test_native_scheduler_compiles():
    """The C++ scheduler must actually build in this toolchain image."""
    assert using_native_scheduler(), "native scheduler failed to compile"
    # Parity with the Python fallback on a random DAG.
    from triton_distributed_tpu.megakernel.scheduler import _topo_python

    rng = np.random.default_rng(0)
    n = 50
    edges = [(int(a), int(b)) for a, b in
             rng.integers(0, n, size=(120, 2)) if a < b]
    assert topo_schedule(n, edges) == _topo_python(n, edges)


def test_megakernel_mlp_single_device():
    """SwiGLU MLP block as one task queue on one device."""
    mb = MegaKernelBuilder()
    m, h, f = 128, 256, 384
    x = mb.tensor(m, h)
    wg = mb.tensor(h, f)
    wu = mb.tensor(h, f)
    wd = mb.tensor(f, h)
    gate = mb.tensor(m, f)
    up = mb.tensor(m, f)
    act = mb.tensor(m, f)
    out = mb.tensor(m, h)
    mb.gemm(gate, x, wg)
    mb.gemm(up, x, wu)
    mb.silu_mul(act, gate, up)
    mb.gemm(out, act, wd)

    prog = mb.compile()
    rng = np.random.default_rng(0)
    ax = rng.standard_normal((m, h)).astype(np.float32) * 0.2
    awg = rng.standard_normal((h, f)).astype(np.float32) * 0.1
    awu = rng.standard_normal((h, f)).astype(np.float32) * 0.1
    awd = rng.standard_normal((f, h)).astype(np.float32) * 0.1

    (got,) = prog.run({x: jnp.asarray(ax), wg: jnp.asarray(awg),
                       wu: jnp.asarray(awu), wd: jnp.asarray(awd)},
                      outputs=[out])
    g = ax @ awg
    ref = (g / (1 + np.exp(-g)) * (ax @ awu)) @ awd
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)


def test_megakernel_tp_allreduce(ctx):
    """Row-parallel GEMM partials + the AllReduce task across the 8-mesh —
    the reference's make_allreduce path (one launch per device)."""
    n, m, k, cols = 8, 128, 128, 128
    mb = MegaKernelBuilder()
    x = mb.tensor(m, k)       # per-device k-shard activation
    w = mb.tensor(k, cols)    # per-device weight rows
    y = mb.tensor(m, cols)
    mb.gemm(y, x, w)
    mb.all_reduce(y)
    prog = mb.compile(num_ranks=n, axis="tp")

    rng = np.random.default_rng(1)
    ax = rng.standard_normal((n, m, k)).astype(np.float32) * 0.2
    aw = rng.standard_normal((n, k, cols)).astype(np.float32) * 0.2

    fn = shard_map_on(
        ctx,
        lambda xl, wl: prog.run({x: xl[0], w: wl[0]}, outputs=[y])[0][None],
        (P("tp"), P("tp")), P("tp"))
    got = np.asarray(fn(jnp.asarray(ax), jnp.asarray(aw)))

    ref = sum(ax[d] @ aw[d] for d in range(n))
    for d in range(n):
        np.testing.assert_allclose(got[d], ref, rtol=2e-3, atol=2e-3)
