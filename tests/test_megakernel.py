"""MegaKernel tests: scheduler, single-device task programs, and the
cross-device AllReduce task (TP MLP block in ONE kernel launch)."""

import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.megakernel import (
    MegaKernelBuilder, topo_schedule, using_native_scheduler,
)
from triton_distributed_tpu.runtime.context import shard_map_on


def test_scheduler_orders_and_detects_cycles():
    order = topo_schedule(4, [(0, 2), (1, 2), (2, 3)])
    assert order.index(2) > max(order.index(0), order.index(1))
    assert order.index(3) > order.index(2)
    with pytest.raises(ValueError, match="cycle"):
        topo_schedule(2, [(0, 1), (1, 0)])


def test_native_scheduler_compiles():
    """The C++ scheduler must actually build in this toolchain image."""
    assert using_native_scheduler(), "native scheduler failed to compile"
    # Parity with the Python fallback on a random DAG.
    from triton_distributed_tpu.megakernel.scheduler import _topo_python

    rng = np.random.default_rng(0)
    n = 50
    edges = [(int(a), int(b)) for a, b in
             rng.integers(0, n, size=(120, 2)) if a < b]
    assert topo_schedule(n, edges) == _topo_python(n, edges)


def test_megakernel_mlp_single_device():
    """SwiGLU MLP block as one task queue on one device."""
    mb = MegaKernelBuilder()
    m, h, f = 128, 256, 384
    x = mb.tensor(m, h)
    wg = mb.tensor(h, f)
    wu = mb.tensor(h, f)
    wd = mb.tensor(f, h)
    gate = mb.tensor(m, f)
    up = mb.tensor(m, f)
    act = mb.tensor(m, f)
    out = mb.tensor(m, h)
    mb.gemm(gate, x, wg)
    mb.gemm(up, x, wu)
    mb.silu_mul(act, gate, up)
    mb.gemm(out, act, wd)

    prog = mb.compile()
    rng = np.random.default_rng(0)
    ax = rng.standard_normal((m, h)).astype(np.float32) * 0.2
    awg = rng.standard_normal((h, f)).astype(np.float32) * 0.1
    awu = rng.standard_normal((h, f)).astype(np.float32) * 0.1
    awd = rng.standard_normal((f, h)).astype(np.float32) * 0.1

    (got,) = prog.run({x: jnp.asarray(ax), wg: jnp.asarray(awg),
                       wu: jnp.asarray(awu), wd: jnp.asarray(awd)},
                      outputs=[out])
    g = ax @ awg
    ref = (g / (1 + np.exp(-g)) * (ax @ awu)) @ awd
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)


def test_megakernel_tp_allreduce(ctx):
    """Row-parallel GEMM partials + the AllReduce task across the 8-mesh —
    the reference's make_allreduce path (one launch per device)."""
    n, m, k, cols = 8, 128, 128, 128
    mb = MegaKernelBuilder()
    x = mb.tensor(m, k)       # per-device k-shard activation
    w = mb.tensor(k, cols)    # per-device weight rows
    y = mb.tensor(m, cols)
    mb.gemm(y, x, w)
    mb.all_reduce(y)
    prog = mb.compile(num_ranks=n, axis="tp")

    rng = np.random.default_rng(1)
    ax = rng.standard_normal((n, m, k)).astype(np.float32) * 0.2
    aw = rng.standard_normal((n, k, cols)).astype(np.float32) * 0.2

    fn = shard_map_on(
        ctx,
        lambda xl, wl: prog.run({x: xl[0], w: wl[0]}, outputs=[y])[0][None],
        (P("tp"), P("tp")), P("tp"))
    got = np.asarray(fn(jnp.asarray(ax), jnp.asarray(aw)))

    ref = sum(ax[d] @ aw[d] for d in range(n))
    for d in range(n):
        np.testing.assert_allclose(got[d], ref, rtol=2e-3, atol=2e-3)


def test_megakernel_paged_attention_task():
    """ATTN_DECODE_PAGED: the page-table walk (table in queue DATA rows,
    pages scattered arbitrarily in the workspace) matches the linear
    ATTN_DECODE task on the same logical cache (VERDICT r2 §2.7 gap)."""
    from triton_distributed_tpu.megakernel.tasks import TILE

    d = TILE
    S = 3 * TILE                     # 3 logical pages
    valid = 2 * TILE + 40
    rng = np.random.default_rng(0)
    q_np = rng.standard_normal((TILE, d)).astype(np.float32) * 0.3
    kT_np = rng.standard_normal((d, S)).astype(np.float32) * 0.3
    v_np = rng.standard_normal((S, d)).astype(np.float32) * 0.3
    k_new = rng.standard_normal((TILE, d)).astype(np.float32) * 0.3
    v_new = rng.standard_normal((TILE, d)).astype(np.float32) * 0.3

    def build(paged: bool):
        mb = MegaKernelBuilder()
        q = mb.tensor(TILE, d)
        kn = mb.tensor(TILE, d)
        vn = mb.tensor(TILE, d)
        out = mb.tensor(TILE, d)
        if paged:
            # Pages allocated as separate scattered tensors, deliberately
            # out of logical order in the workspace.
            kt_pages = [mb.tensor(d, TILE) for _ in range(3)]
            v_pages = [mb.tensor(TILE, d) for _ in range(3)]
            pages = [(kt_pages[j].tile(0, 0), v_pages[j].tile(0, 0))
                     for j in range(3)]
            mb.attn_decode_paged(out, q, pages, valid_len=valid,
                                 scale=d ** -0.5, k_new=kn, v_new=vn)
            feeds = {q: q_np, kn: k_new, vn: v_new}
            for j in range(3):
                feeds[kt_pages[j]] = kT_np[:, j * TILE:(j + 1) * TILE]
                feeds[v_pages[j]] = v_np[j * TILE:(j + 1) * TILE]
        else:
            kT = mb.tensor(d, S)
            v = mb.tensor(S, d)
            mb.attn_decode(out, q, kT, v, valid_len=valid, scale=d ** -0.5,
                           k_new=kn, v_new=vn)
            feeds = {q: q_np, kT: kT_np, v: v_np, kn: k_new, vn: v_new}
        comp = mb.compile()
        feeds = {h: jnp.asarray(val) for h, val in feeds.items()}
        (res,) = comp.run(feeds, outputs=[out])
        return np.asarray(res)

    linear = build(paged=False)
    paged = build(paged=True)
    np.testing.assert_allclose(paged, linear, rtol=1e-5, atol=1e-5)

    # Numpy golden: softmax over valid cache positions + current token.
    s = np.concatenate([q_np @ kT_np[:, :valid],
                        (q_np * k_new).sum(-1, keepdims=True)],
                       axis=1) * d ** -0.5
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    gold = p[:, :valid] @ v_np[:valid] + p[:, valid:] * v_new
    np.testing.assert_allclose(paged, gold, rtol=2e-4, atol=2e-4)


def test_megakernel_prefetch_task():
    """PREFETCH + gemm(prefetch_first=True): the warmed first weight tile
    path produces the same result as the plain gemm, and the builder
    rejects mismatched/double prefetches (VERDICT r2 §2.7 gap)."""
    from triton_distributed_tpu.megakernel.tasks import TILE

    rng = np.random.default_rng(1)
    a_np = rng.standard_normal((TILE, 2 * TILE)).astype(np.float32) * 0.2
    b_np = rng.standard_normal((2 * TILE, TILE)).astype(np.float32) * 0.2

    def build(pf: bool):
        mb = MegaKernelBuilder()
        a = mb.tensor(TILE, 2 * TILE)
        b = mb.tensor(2 * TILE, TILE)
        out = mb.tensor(TILE, TILE)
        if pf:
            mb.prefetch(b.tile(0, 0))
        mb.gemm(out, a, b, prefetch_first=pf)
        comp = mb.compile()
        (res,) = comp.run({a: jnp.asarray(a_np), b: jnp.asarray(b_np)},
                          outputs=[out])
        return np.asarray(res)

    np.testing.assert_allclose(build(True), build(False), rtol=1e-6)
    np.testing.assert_allclose(build(False), a_np @ b_np, rtol=1e-4,
                               atol=1e-4)

    mb = MegaKernelBuilder()
    a = mb.tensor(TILE, TILE)
    b = mb.tensor(TILE, TILE)
    out = mb.tensor(TILE, TILE)
    with pytest.raises(ValueError, match="does not match"):
        mb.prefetch(a.tile(0, 0))
        mb.gemm(out, a, b, prefetch_first=True)
    mb2 = MegaKernelBuilder()
    with pytest.raises(ValueError, match="not yet consumed"):
        mb2.prefetch(a.tile(0, 0))
        mb2.prefetch(b.tile(0, 0))


def test_gemm_wide_strips_and_prefetch():
    """GEMM_WIDE: a (256, 640) output at width=3 splits into 3+2 strips per
    row tile; values match numpy, and the prefetch warm feeds strip 0's
    first weight tile."""
    from triton_distributed_tpu.megakernel.tasks import TILE, TaskType

    mb = MegaKernelBuilder()
    m, k, n = 2 * TILE, 3 * TILE, 5 * TILE
    x = mb.tensor(m, k)
    w = mb.tensor(k, n)
    out = mb.tensor(m, n)
    mb.prefetch(w.tile(0, 0))
    mb.gemm(out, x, w, prefetch_first=True, width=3)
    prog = mb.compile()
    wide = [t for t in np.asarray(prog.queue)
            if t[0] == int(TaskType.GEMM_WIDE)]
    assert sorted(t[7] for t in wide) == [2, 2, 3, 3]   # widths per strip
    assert prog.max_gemm_width == 3

    rng = np.random.default_rng(3)
    ax = rng.standard_normal((m, k)).astype(np.float32)
    aw = rng.standard_normal((k, n)).astype(np.float32) * 0.1
    (res,) = prog.run({x: jnp.asarray(ax), w: jnp.asarray(aw)},
                      outputs=[out])
    np.testing.assert_allclose(np.asarray(res), ax @ aw, rtol=2e-4,
                               atol=2e-4)


def test_append_kv_task_and_retarget():
    """APPEND_KV writes k_new row 0 into the kT column / v row at pos, and
    advance_queue_pos retargets the destination tile + column without
    recompiling."""
    from triton_distributed_tpu.megakernel.models import advance_queue_pos
    from triton_distributed_tpu.megakernel.tasks import TILE

    mb = MegaKernelBuilder()
    S = 2 * TILE
    kT = mb.tensor(TILE, S)
    v = mb.tensor(S, TILE)
    k_new = mb.tensor(TILE, TILE)
    v_new = mb.tensor(TILE, TILE)
    build_pos = S - 1
    mb.append_kv(kT, v, build_pos, k_new, v_new)
    prog = mb.compile()

    rng = np.random.default_rng(4)
    feeds = {kT: rng.standard_normal((TILE, S)).astype(np.float32),
             v: rng.standard_normal((S, TILE)).astype(np.float32),
             k_new: rng.standard_normal((TILE, TILE)).astype(np.float32),
             v_new: rng.standard_normal((TILE, TILE)).astype(np.float32)}
    jf = {h: jnp.asarray(a) for h, a in feeds.items()}

    for pos in (build_pos, 5, TILE + 17):   # build pos + two retargets
        queue = advance_queue_pos(prog, pos)
        ws = prog.step(prog.make_workspace(jf), queue)
        got_k = np.asarray(prog.gather_output(ws, kT))
        got_v = np.asarray(prog.gather_output(ws, v))
        want_k = feeds[kT].copy()
        want_k[:, pos] = feeds[k_new][0]
        want_v = feeds[v].copy()
        want_v[pos, :] = feeds[v_new][0]
        np.testing.assert_allclose(got_k, want_k, rtol=1e-6)
        np.testing.assert_allclose(got_v, want_v, rtol=1e-6)


def test_megakernel_fp8_weight_workspace():
    """GEMM_WIDE_W8 + PREFETCH_W8: weights stream from the float8_e4m3fn
    workspace (half the bytes) and the result matches the golden computed
    on the e4m3-quantized weights exactly (fp32 compute path)."""
    mb = MegaKernelBuilder()
    m, k, n = 128, 256, 640
    x = mb.tensor(m, k)
    w = mb.tensor(k, n, fp8=True)
    out = mb.tensor(m, n)
    mb.prefetch(w.tile(0, 0), fp8=True)
    mb.gemm(out, x, w, prefetch_first=True, width=3)
    prog = mb.compile()
    assert prog.num_tiles8 == (k // 128) * (n // 128)

    rng = np.random.default_rng(9)
    ax = rng.standard_normal((m, k)).astype(np.float32)
    aw = rng.standard_normal((k, n)).astype(np.float32) * 0.1
    (res,) = prog.run({x: jnp.asarray(ax), w: jnp.asarray(aw)},
                      outputs=[out])
    w_q = np.asarray(jnp.asarray(aw).astype(jnp.float8_e4m3fn)
                     .astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(res), ax @ w_q, rtol=2e-4,
                               atol=2e-4)


def test_fp8_handles_rejected_outside_gemm_b():
    """fp8 weight-space handles alias main-workspace tile ids — every op
    except the GEMM B operand must reject them at build time."""
    mb = MegaKernelBuilder()
    x = mb.tensor(128, 128)
    w8 = mb.tensor(128, 128, fp8=True)
    with pytest.raises(ValueError, match="fp8"):
        mb.add(x, x, w8)
    with pytest.raises(ValueError, match="fp8"):
        mb.rms_norm(x, x, w8)
    with pytest.raises(ValueError, match="fp8"):
        mb.gemm(w8, x, x)     # fp8 as output
    with pytest.raises(ValueError, match="fp8"):
        mb.gemm(x, w8, x)     # fp8 as activation


def test_compiled_program_prunes_unused_handler_branches():
    """compile() records the queue's task-type set and step() compiles
    every other switch branch as a no-op (round-6 build-latency lever).
    The pruned program must still execute its own tasks correctly, and
    advance_queue_pos (the only sanctioned queue mutation) must never
    introduce a type outside the recorded set."""
    from triton_distributed_tpu.megakernel.tasks import TaskType

    mb = MegaKernelBuilder()
    a = mb.tensor(128, 128)
    b = mb.tensor(128, 128)
    out = mb.tensor(128, 128)
    mb.add(out, a, b)
    prog = mb.compile()
    assert prog.used_types == (int(TaskType.ADD),)

    rng = np.random.default_rng(11)
    av = rng.standard_normal((128, 128)).astype(np.float32)
    bv = rng.standard_normal((128, 128)).astype(np.float32)
    (res,) = prog.run({a: jnp.asarray(av), b: jnp.asarray(bv)},
                      outputs=[out])
    np.testing.assert_allclose(np.asarray(res), av + bv, rtol=1e-6)

    queue_types = set(np.asarray(prog.queue)[:prog.num_exec, 0].tolist())
    assert queue_types == set(prog.used_types)
