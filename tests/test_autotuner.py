"""Contextual autotuner tests (reference autotuner.py:43-105 behavior)."""

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.runtime.autotuner import (
    contextual_autotune,
    gemm_tile_candidates,
    tune_ag_gemm,
)


def test_autotune_picks_fastest_and_caches(tmp_path, monkeypatch):
    monkeypatch.setenv("TDTPU_AUTOTUNE_CACHE", str(tmp_path / "cache.json"))
    import time

    calls = []

    def build(cfg):
        def fn(x):
            calls.append(cfg)
            time.sleep(0.002 * cfg)  # cfg == sleep multiplier
            return x
        return fn

    best, report = contextual_autotune(
        "sleepy", "k1", [3, 1, 2], build, (jnp.zeros((4,)),), iters=2)
    assert best == 1
    assert report.best_index == 1
    assert all(t is not None for t in report.timings)

    # Cache hit: no new measurements.
    before = len(calls)
    best2, report2 = contextual_autotune(
        "sleepy", "k1", [3, 1, 2], build, (jnp.zeros((4,)),), iters=2)
    assert best2 == 1 and report2 is None and len(calls) == before


def test_autotune_prunes_failing_candidates(tmp_path, monkeypatch):
    monkeypatch.setenv("TDTPU_AUTOTUNE_CACHE", str(tmp_path / "c.json"))

    def build(cfg):
        if cfg == "bad":
            raise RuntimeError("does not compile")
        return lambda x: x

    best, report = contextual_autotune(
        "pruney", "k", ["bad", "good"], build, (jnp.zeros((2,)),))
    assert best == "good"
    assert report.timings[0] is None

    with pytest.raises(RuntimeError, match="every candidate failed"):
        contextual_autotune("pruney", "k2", ["bad"], build,
                            (jnp.zeros((2,)),))


def test_gemm_tile_candidates_fit():
    cands = gemm_tile_candidates(256, 512, 1024, itemsize=4)
    assert cands
    for tm, tn, tk in cands:
        assert tm <= 256 and tn <= 1024 and tk <= 512


def test_tune_ag_gemm_end_to_end(ctx, tmp_path, monkeypatch):
    """Tunes the real distributed op on the CPU mesh (tiny space)."""
    monkeypatch.setenv("TDTPU_AUTOTUNE_CACHE", str(tmp_path / "c.json"))
    n, m, k, cols = 8, 16, 128, 128
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n * m, k)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n * cols)), jnp.float32)
    cfg = tune_ag_gemm(a, b, ctx)
    assert cfg.tile_m <= m and cfg.tile_k <= k


def test_measure_chain_ranks_work():
    """Chain-differential timing (the axon-relay-safe measure) separates a
    cheap op from a 64x-heavier one and survives non-square outputs."""
    from triton_distributed_tpu.runtime.autotuner import measure_chain

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)

    def cheap(x, w):
        return x @ w                      # (64, 128): not x's shape

    def heavy(x, w):
        y = x @ w
        for _ in range(63):
            y = y + x @ w
        return y

    # Wall-clock on a loaded CI host is jumpy: retry the whole measurement
    # a few times before declaring the ranking broken (a transient
    # non-positive differential under CPU contention is not a bug).
    last = None
    for _ in range(3):
        try:
            t_cheap = measure_chain(cheap, (x, w), lengths=(4, 64), trials=2)
            t_heavy = measure_chain(heavy, (x, w), lengths=(4, 64), trials=2)
            if t_heavy > t_cheap:
                return
            last = AssertionError(f"heavy {t_heavy} !> cheap {t_cheap}")
        except RuntimeError as e:   # non-positive differential
            last = e
    raise last


def test_default_cfg_resolution_off_chip(monkeypatch):
    """cfg=None resolves to the static defaults when tuning is off, and the
    tuned-matmul entry answers correctly."""
    from triton_distributed_tpu.ops.allgather_gemm import (
        AGGemmConfig, resolve_gemm_cfg,
    )
    from triton_distributed_tpu.ops.gemm import pallas_matmul_tuned
    from triton_distributed_tpu.runtime.autotuner import autotune_enabled

    monkeypatch.setenv("TDTPU_AUTOTUNE", "0")   # force off even on TPU hosts
    assert not autotune_enabled()
    cfg = resolve_gemm_cfg(None, AGGemmConfig, 256, 512, 512, jnp.float32)
    assert cfg == AGGemmConfig()
    assert resolve_gemm_cfg(AGGemmConfig(tile_m=128), AGGemmConfig,
                            256, 512, 512, jnp.float32).tile_m == 128
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.standard_normal((32, 64)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
    np.testing.assert_allclose(np.asarray(pallas_matmul_tuned(a, b)),
                               np.asarray(a) @ np.asarray(b), rtol=1e-5,
                               atol=1e-5)


def test_tuned_flash_tiles_off_chip(monkeypatch):
    """Flash-tile tuning is chip-measured only: with tuning disabled the
    entry returns None and callers keep the swept defaults."""
    from triton_distributed_tpu.runtime.autotuner import tuned_flash_tiles

    monkeypatch.setenv("TDTPU_AUTOTUNE", "0")   # force off even on TPU hosts
    assert tuned_flash_tiles(1024, 1024, 8, 1, 128, jnp.bfloat16) is None


def test_comm_tuning_cache_roundtrip(ctx, tmp_path, monkeypatch):
    """Comm-side tuning (TDTPU_AUTOTUNE_COMM): the AR one/two-shot/xla
    crossover is measured through the real whole-mesh thunk, the winner is
    disk-cached, and a second resolution is a pure cache hit (no
    re-measure). Block timing on the CPU mesh exercises the MACHINERY —
    the measured decision is only meaningful on real hardware."""
    import jax.numpy as jnp

    from triton_distributed_tpu.runtime import autotuner

    monkeypatch.setenv("TDTPU_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    autotuner._memory_cache.clear()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 16, 128)), jnp.float32)

    best = autotuner.tuned_allreduce_method(x, ctx, axis="tp",
                                            method="block")
    assert best in ("one_shot", "two_shot", "xla")

    # Second resolution must be a cache hit: contextual_autotune returns a
    # None report on hits, and the memory cache must already hold the key.
    calls = []
    orig = autotuner.measure

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(autotuner, "measure", spy)
    best2 = autotuner.tuned_allreduce_method(x, ctx, axis="tp",
                                             method="block")
    assert best2 == best
    assert not calls, "cache hit must not re-measure"

    # Cross-process persistence: a fresh memory cache resolves from disk.
    autotuner._memory_cache.clear()
    best3 = autotuner.tuned_allreduce_method(x, ctx, axis="tp",
                                             method="block")
    assert best3 == best
    assert not calls

    # A2A block-rows tuning rides the same machinery.
    sb = jnp.asarray(rng.standard_normal((8, 8, 32, 64)), jnp.float32)
    sp = jnp.asarray(np.full((8, 8, 2), 2), np.int32)
    b = autotuner.tuned_a2a_block_rows(sb, sp, ctx, axis="tp",
                                       method="block")
    assert b in (16, 32)


def test_tuned_gemm_ar_path_off_by_default(ctx, monkeypatch):
    """With comm tuning off the selector returns None and the Engine
    default stays the measured-safe dot+AR (VERDICT r4 #2: the fused path
    must never be picked blindly)."""
    monkeypatch.delenv("TDTPU_AUTOTUNE_COMM", raising=False)
    from triton_distributed_tpu.runtime.autotuner import tuned_gemm_ar_path

    assert tuned_gemm_ar_path(1, 64, 256, jnp.float32, ctx) is None


def test_engine_fused_gemm_ar_flag(ctx, monkeypatch):
    """TDTPU_GEMM_AR pins the path; unset defaults to dot_ar when no
    measurement is available."""
    import jax

    from triton_distributed_tpu.models.config import ModelConfig
    from triton_distributed_tpu.models.dense import init_dense_llm
    from triton_distributed_tpu.models.engine import Engine

    cfg = ModelConfig(hidden_size=256, intermediate_size=256, num_layers=1,
                      num_heads=16, num_kv_heads=8, head_dim=16,
                      vocab_size=128)
    params = init_dense_llm(jax.random.key(0), cfg)
    eng = Engine(cfg, params, ctx, max_seq=32)
    monkeypatch.delenv("TDTPU_AUTOTUNE_COMM", raising=False)
    monkeypatch.setenv("TDTPU_GEMM_AR", "1")
    assert eng._use_fused_gemm_ar() is True
    monkeypatch.setenv("TDTPU_GEMM_AR", "0")
    assert eng._use_fused_gemm_ar() is False
    monkeypatch.delenv("TDTPU_GEMM_AR", raising=False)
    assert eng._use_fused_gemm_ar() is False   # auto, no measurement
    assert eng._gemm_ar_choice == "dot_ar"
