"""Two-level (ICI intra + DCN inter) collectives on a (2, 4) CPU mesh —
the inter-slice tier the reference covers with NVSHMEM/IB (SURVEY.md §7)."""

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops.two_level import (
    all_gather_2d,
    all_reduce_2d,
    reduce_scatter_2d,
)
from triton_distributed_tpu.runtime.context import initialize_distributed


@pytest.fixture(scope="module")
def ctx2d():
    """(dcn=2, tp=4) mesh over the 8 virtual CPU devices."""
    return initialize_distributed(mesh_shape=(2, 4),
                                  axis_names=("dcn", "tp"))


def test_all_gather_2d_golden(ctx2d):
    N, m, cols = 8, 16, 128
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((N * m, cols)), jnp.float32)
    out = all_gather_2d(x, ctx2d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=0, atol=0)


def test_all_reduce_2d_golden(ctx2d):
    N, m, cols = 8, 32, 128
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((N, m, cols)), jnp.float32)
    out = all_reduce_2d(x, ctx2d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0),
                               rtol=1e-4, atol=1e-4)


def test_reduce_scatter_2d_golden(ctx2d):
    N, m, cols = 8, 16, 128
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((N, N * m, cols)), jnp.float32)
    out = reduce_scatter_2d(x, ctx2d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0),
                               rtol=1e-4, atol=1e-4)


def test_pallas_ops_work_on_tp_axis_of_2d_mesh(ctx2d):
    """Pallas remote DMA on the intra axis of a multi-axis mesh — exercises
    the peer_id coordinate translation (language/distributed_ops.py)."""
    from triton_distributed_tpu.ops import ag_gemm

    n = 4
    m, k, cols = 8, 128, 128
    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.standard_normal((n * m, k)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n * cols)) * 0.1, jnp.float32)
    out = ag_gemm(a, b, ctx2d, axis="tp")
    ref = np.asarray(a) @ np.asarray(b)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)


def test_fast_all_to_all_2d_golden(ctx2d):
    """Hierarchical EP A2A (DCN hop + per-slice Pallas A2A) delivers the
    identical slot layout as a global shuffle golden."""
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.ops.two_level import fast_all_to_all_2d_local
    from triton_distributed_tpu.runtime.context import shard_map_on

    N, cap, hidden, epr = 8, 16, 64, 2
    rng = np.random.default_rng(3)
    # Global stacked view: send[g] is rank g's (N, cap, hidden) send buffer.
    send = jnp.asarray(rng.standard_normal((N, N, cap, hidden)), jnp.float32)
    counts = rng.integers(0, cap // epr, size=(N, N, epr)).astype(np.int32)
    splits = jnp.asarray(counts)

    def run(sb, sp):
        rb, rs = fast_all_to_all_2d_local(sb[0], sp[0], n_intra=4, n_inter=2)
        return rb[None], rs[None]

    fn = shard_map_on(ctx2d, run,
                      (P(("dcn", "tp")), P(("dcn", "tp"))),
                      (P(("dcn", "tp")), P(("dcn", "tp"))))
    rb, rs = fn(send, splits)
    rb, rs = np.asarray(rb), np.asarray(rs)
    send_np = np.asarray(send)
    for dst in range(N):
        for src in range(N):
            used = counts[src, dst].sum()
            np.testing.assert_allclose(rb[dst, src, :used],
                                       send_np[src, dst, :used], rtol=0,
                                       err_msg=f"dst {dst} src {src}")
            np.testing.assert_array_equal(rs[dst, src], counts[src, dst])


def test_sp_ag_attention_2d_golden(ctx2d):
    """Hierarchical SP attention (intra Pallas AG + one DCN crossing per
    slice) matches the dense causal golden over the full sequence."""
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.ops.flash_attention import _block_attn
    from triton_distributed_tpu.ops.two_level import sp_ag_attention_2d_local
    from triton_distributed_tpu.runtime.context import shard_map_on

    N, b, s, hq, hkv, d = 8, 1, 256, 4, 2, 64
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)) * 0.3, jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)) * 0.3, jnp.float32)

    fn = shard_map_on(
        ctx2d,
        lambda qq, kk, vv: sp_ag_attention_2d_local(
            qq, kk, vv, n_intra=4, n_inter=2, causal=True),
        (P(None, ("dcn", "tp")),) * 3, P(None, ("dcn", "tp")))
    out = np.asarray(fn(q, k, v))

    acc, _, l = _block_attn(q, k, v, jnp.tril(jnp.ones((s, s), bool)))
    gold = np.asarray(acc / jnp.maximum(l, 1e-30)[..., None])
    np.testing.assert_allclose(out, gold, rtol=2e-3, atol=2e-3)
