"""Two-level (ICI intra + DCN inter) collectives on a (2, 4) CPU mesh —
the inter-slice tier the reference covers with NVSHMEM/IB (SURVEY.md §7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops.two_level import (
    all_gather_2d,
    all_reduce_2d,
    reduce_scatter_2d,
)
from triton_distributed_tpu.runtime.context import initialize_distributed


@pytest.fixture(scope="module")
def ctx2d():
    """(dcn=2, tp=4) mesh over the 8 virtual CPU devices."""
    return initialize_distributed(mesh_shape=(2, 4),
                                  axis_names=("dcn", "tp"))


def test_all_gather_2d_golden(ctx2d):
    N, m, cols = 8, 16, 128
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((N * m, cols)), jnp.float32)
    out = all_gather_2d(x, ctx2d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=0, atol=0)


def test_all_reduce_2d_golden(ctx2d):
    N, m, cols = 8, 32, 128
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((N, m, cols)), jnp.float32)
    out = all_reduce_2d(x, ctx2d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0),
                               rtol=1e-4, atol=1e-4)


def test_reduce_scatter_2d_golden(ctx2d):
    N, m, cols = 8, 16, 128
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((N, N * m, cols)), jnp.float32)
    out = reduce_scatter_2d(x, ctx2d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0),
                               rtol=1e-4, atol=1e-4)


def test_pallas_ops_work_on_tp_axis_of_2d_mesh(ctx2d):
    """Pallas remote DMA on the intra axis of a multi-axis mesh — exercises
    the peer_id coordinate translation (language/distributed_ops.py)."""
    from triton_distributed_tpu.ops import ag_gemm

    n = 4
    m, k, cols = 8, 128, 128
    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.standard_normal((n * m, k)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n * cols)) * 0.1, jnp.float32)
    out = ag_gemm(a, b, ctx2d, axis="tp")
    ref = np.asarray(a) @ np.asarray(b)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)
