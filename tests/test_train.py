"""TP training step + checkpoint round trip (beyond-reference capability:
the reference is inference-only)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.models.config import tiny_config
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.train import lm_loss, make_train_step


def _batch(rng, cfg, batch=2, seq=12):
    ids = rng.integers(0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    return jnp.asarray(ids[:, :-1]), jnp.asarray(ids[:, 1:])


def test_train_step_reduces_loss(ctx):
    cfg = tiny_config()
    rng = np.random.default_rng(0)
    params = init_dense_llm(jax.random.PRNGKey(0), cfg)
    init_state, train_step = make_train_step(cfg, ctx, learning_rate=3e-3)
    state = init_state(params)

    ids, labels = _batch(rng, cfg)
    losses = []
    for _ in range(8):
        state, loss = train_step(state, ids, labels)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses

    # Grads/updates respected the TP shardings (spot-check a sharded leaf).
    wq = state.params["layers"][0]["attn"]["wq"]
    assert len(wq.sharding.spec) == 2 and wq.sharding.spec[1] == "tp"


def test_train_step_moe(ctx):
    cfg = tiny_config(num_experts=4, num_experts_per_tok=2,
                      moe_intermediate_size=32)
    rng = np.random.default_rng(1)
    params = init_dense_llm(jax.random.PRNGKey(1), cfg)
    init_state, train_step = make_train_step(cfg, ctx, learning_rate=3e-3)
    state = init_state(params)
    ids, labels = _batch(rng, cfg)
    l0 = float(lm_loss(state.params, cfg, ids, labels))
    for _ in range(6):
        state, loss = train_step(state, ids, labels)
    assert float(loss) < l0, (l0, float(loss))


def test_checkpoint_round_trip(ctx, tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from triton_distributed_tpu.models.checkpoint import (
        restore_checkpoint, save_checkpoint,
    )

    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(2), cfg)
    init_state, _ = make_train_step(cfg, ctx)
    state = init_state(params)

    path = save_checkpoint(str(tmp_path / "ck"), state.params)
    restored = restore_checkpoint(path, like=state.params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        state.params, restored)