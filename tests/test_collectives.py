"""Collective correctness vs jax.lax goldens (reference pattern: golden
torch.distributed collectives, SURVEY.md §4 — here jax.lax.all_gather/psum).

Inputs are mutated across iterations to catch stale-buffer bugs
(reference test_ag_gemm.py:86-92)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops import (
    AllGatherMethod,
    AllReduceMethod,
    all_gather,
    all_reduce,
    reduce_scatter,
)


def _rand(shape, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape), dtype=dtype)


@pytest.mark.parametrize("method", [AllGatherMethod.FULL_MESH_PUSH,
                                    AllGatherMethod.RING_1D])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_all_gather(ctx, method, dtype):
    n = ctx.num_ranks
    for it in range(3):  # mutate inputs per iteration (stale-buffer check)
        x = _rand((n * 16, 128), dtype, seed=it)
        got = all_gather(x, ctx, method=method, stacked=True)
        expected = np.broadcast_to(np.asarray(x), (n, n * 16, 128))
        np.testing.assert_array_equal(np.asarray(got), expected)


def test_all_gather_replicated_view(ctx):
    x = _rand((8 * 8, 128))
    got = all_gather(x, ctx, method=AllGatherMethod.RING_1D)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(x))


def test_reduce_scatter(ctx):
    n = ctx.num_ranks
    for it in range(3):
        x = _rand((n, n * 16, 128), seed=10 + it)  # per-device contributions
        got = reduce_scatter(x, ctx)
        expected = np.asarray(x).sum(axis=0)
        np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("method", [AllReduceMethod.ONE_SHOT,
                                    AllReduceMethod.TWO_SHOT,
                                    AllReduceMethod.TREE])
def test_all_reduce(ctx, method):
    n = ctx.num_ranks
    for it in range(2):
        x = _rand((n, 32, 128), seed=20 + it)
        got = all_reduce(x, ctx, method=method)
        expected = np.asarray(x).sum(axis=0)
        np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_all_reduce_tree_single_tree_fallback(ctx):
    """Rows that cannot split into two aligned halves run the one-tree
    variant (m=8 fp32: 8 % (2·8) != 0)."""
    n = ctx.num_ranks
    x = _rand((n, 8, 128), seed=25)
    got = all_reduce(x, ctx, method=AllReduceMethod.TREE)
    np.testing.assert_allclose(np.asarray(got), np.asarray(x).sum(axis=0),
                               rtol=1e-5, atol=1e-5)


def test_all_reduce_tree_bf16(ctx):
    """Double tree on bf16: partials round per level (like ring RS), so
    compare with a loose tolerance."""
    n = ctx.num_ranks
    x = _rand((n, 32, 128), jnp.bfloat16, seed=26)
    got = all_reduce(x, ctx, method=AllReduceMethod.TREE)
    expected = np.asarray(x, dtype=np.float32).sum(axis=0)
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32), expected, rtol=5e-2, atol=5e-2)


def test_all_reduce_bf16_one_shot(ctx):
    """fp32 accumulation inside the one-shot kernel: compare against fp32 sum
    cast to bf16 (bitwise-deterministic reduction order)."""
    n = ctx.num_ranks
    x = _rand((n, 16, 128), jnp.bfloat16, seed=30)
    got = all_reduce(x, ctx, method=AllReduceMethod.ONE_SHOT)
    expected = np.asarray(x, dtype=np.float32).sum(axis=0)
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32), expected, rtol=2e-2, atol=2e-2)


def test_ll_allgather_layer_buckets(ctx):
    """Decode comm layer: bucketed low-latency AG must strip pad rows and
    reuse compiled buckets across close shapes (reference
    low_latency_allgather_layer staged-buffer analog)."""
    from triton_distributed_tpu.ops import AllGatherLayer

    layer = AllGatherLayer(ctx)
    rng = np.random.default_rng(9)
    n = 8
    for m_local in (3, 5, 8, 13):   # 3/5 share the 8-bucket; 13 -> 16
        x = jnp.asarray(rng.standard_normal((n * m_local, 128)), jnp.float32)
        out = layer(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                                   rtol=0, atol=0, err_msg=f"m={m_local}")


def test_ar_stream_parity_correct_and_barrier_free(ctx):
    """Barrier-free parity AR (VERDICT r2 #6): many repeated calls over ONE
    persistent workspace, a rotating straggler widening every reuse window,
    every call's sum exact. The kernel contains no barrier_all — correctness
    rests purely on the parity + DMA-completion-chain protocol."""
    from triton_distributed_tpu.ops.allreduce import (
        all_reduce_stream, ar_stream_workspace,
    )
    from triton_distributed_tpu.runtime import shard_map_on

    n, m, cols, steps = 8, 8, 128, 1000
    rng = np.random.default_rng(0)
    base = rng.standard_normal((n, m, cols)).astype(np.float32)
    want_base = base.sum(axis=0)

    def run(xl):
        xl = xl[0]                       # (m, cols) this rank's block
        ws, idx = ar_stream_workspace(n, m, cols, xl.dtype)

        def body(t, carry):
            ws, idx, err = carry
            x_t = xl * (1.0 + t)
            out, ws, idx = all_reduce_stream(
                x_t, ws, idx, axis="tp", num_ranks=n,
                straggler=("rotate", 256))
            return ws, idx, jnp.maximum(
                err, jnp.max(jnp.abs(out / (1.0 + t) - want_ref)))

        want_ref = jnp.asarray(want_base)
        _, idx, err = jax.lax.fori_loop(
            0, steps, body, (ws, idx, jnp.float32(0)))
        return err[None], idx[None]

    from jax.sharding import PartitionSpec as P

    fn = shard_map_on(ctx, run, P("tp"), (P("tp"), P("tp")))
    err, idx = fn(jnp.asarray(base))
    assert float(np.max(np.asarray(err))) < 1e-3, float(np.max(np.asarray(err)))
    assert int(np.asarray(idx)[0]) == steps


def test_fixed_straggler_rank_result_exact(ctx):
    """maybe_straggle fault injection with a FIXED (rank, cycles) pair: one
    rank spins inside the kernel (the ``@pl.when(me == s_rank)`` +
    ``pl.delay`` path, distinct from the rotating form whose rank is
    traced) and the collective must still be exact — the spin only widens
    the race window, it must never change the protocol outcome."""
    from triton_distributed_tpu.ops.allgather import (
        ag_stream_workspace, all_gather_stream,
    )
    from triton_distributed_tpu.runtime import shard_map_on
    from jax.sharding import PartitionSpec as P

    n, m, cols = 8, 16, 128
    rng = np.random.default_rng(11)
    base = rng.standard_normal((n, m, cols)).astype(np.float32)
    want = jnp.asarray(base.reshape(n * m, cols))

    def run(xl):
        xl = xl[0]
        ws, idx = ag_stream_workspace(n, m, cols, xl.dtype)
        err = jnp.float32(0)
        for t in range(3):   # straggler on both parities + a reuse step
            out, ws, idx = all_gather_stream(
                xl * (1.0 + t), ws, idx, axis="tp", num_ranks=n,
                straggler=(1, 512))
            # AG only moves bytes, so compare against the identically
            # computed product — bit-exact, no division roundtrip.
            err = jnp.maximum(err, jnp.max(jnp.abs(out - want * (1.0 + t))))
        return err[None], idx[None]

    fn = shard_map_on(ctx, run, P("tp"), (P("tp"), P("tp")))
    err, idx = fn(jnp.asarray(base))
    assert float(np.max(np.asarray(err))) == 0.0, float(np.max(np.asarray(err)))
    assert int(np.asarray(idx)[0]) == 3


def test_ag_stream_parity_repeated_calls(ctx):
    """Barrier-free parity AllGather: repeated calls over one persistent
    workspace with a rotating straggler stay exact (same protocol + safety
    chain as the AR stream)."""
    from triton_distributed_tpu.ops.allgather import (
        ag_stream_workspace, all_gather_stream,
    )
    from triton_distributed_tpu.runtime import shard_map_on
    from jax.sharding import PartitionSpec as P

    n, m, cols, steps = 8, 16, 128, 200
    rng = np.random.default_rng(5)
    base = rng.standard_normal((n, m, cols)).astype(np.float32)
    want = jnp.asarray(base.reshape(n * m, cols))

    def run(xl):
        xl = xl[0]
        ws, idx = ag_stream_workspace(n, m, cols, xl.dtype)

        def body(t, carry):
            ws, idx, err = carry
            out, ws, idx = all_gather_stream(
                xl * (1.0 + t), ws, idx, axis="tp", num_ranks=n,
                straggler=("rotate", 256))
            return ws, idx, jnp.maximum(
                err, jnp.max(jnp.abs(out / (1.0 + t) - want)))

        _, idx, err = jax.lax.fori_loop(0, steps, body,
                                        (ws, idx, jnp.float32(0)))
        return err[None], idx[None]

    fn = shard_map_on(ctx, run, P("tp"), (P("tp"), P("tp")))
    err, idx = fn(jnp.asarray(base))
    assert float(np.max(np.asarray(err))) < 1e-4, float(np.max(np.asarray(err)))
    assert int(np.asarray(idx)[0]) == steps


def test_decode_layers_sp_flash_and_gemm_ar(ctx):
    """Decode comm layers (reference SpGQAFlashDecodeAttention /
    GemmARLayer): stream-stateful wrappers match the stateless goldens
    across repeated steps."""
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.layers.decode_layers import (
        GemmARLayer, SpFlashDecodeAttention,
    )
    from triton_distributed_tpu.ops.flash_decode import flash_decode_local
    from triton_distributed_tpu.runtime import shard_map_on

    n, b, hq, hkv, d, s_shard = 8, 2, 4, 2, 64, 32
    m, kloc, cols = 8, 16, 128
    rng = np.random.default_rng(3)
    q = rng.standard_normal((b, hq, d)).astype(np.float32)
    k = rng.standard_normal((n, b, s_shard, hkv, d)).astype(np.float32)
    v = rng.standard_normal((n, b, s_shard, hkv, d)).astype(np.float32)
    x = rng.standard_normal((n, m, kloc)).astype(np.float32)
    w = rng.standard_normal((n, kloc, cols)).astype(np.float32)

    def run(ql, kl, vl, xl, wl):
        kl, vl, xl, wl = kl[0], vl[0], xl[0], wl[0]
        attn = SpFlashDecodeAttention(num_ranks=n)
        st = attn.init_state(b, hq, d)
        proj = GemmARLayer(num_ranks=n)
        pst = proj.init_state(m, cols)
        for _ in range(2):
            o1, st = attn(ql, kl, vl, jnp.int32(s_shard), st)
            y1, pst = proj(xl, wl, pst)
        ref_o = flash_decode_local(ql, kl, vl, jnp.int32(s_shard),
                                   num_ranks=n, method="xla")
        ref_y = jax.lax.psum(xl @ wl, "tp")
        return o1, y1, ref_o, ref_y

    fn = shard_map_on(ctx, run, (P(), P("tp"), P("tp"), P("tp"), P("tp")),
                      (P(), P(), P(), P()))
    o1, y1, ref_o, ref_y = fn(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v), jnp.asarray(x),
                              jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(o1), np.asarray(ref_o),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(ref_y),
                               rtol=1e-4, atol=1e-4)
