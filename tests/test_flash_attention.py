"""Tiled Pallas flash-attention prefill vs dense goldens (interpret mode).

Covers the multi-tile grid (several q and k tiles), GQA head mapping, causal
positional offsets (the ring-attention contract), dead-shard skip, the
partial (acc, m, l) merge contract, and the dense fallback dispatcher.
"""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops.flash_attention import (
    _block_attn,
    _merge,
    flash_attention,
    flash_attention_partial,
    flash_supported,
    shard_attention,
    shard_attention_partial,
)


def _dense(q, k, v, mask):
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    qf = q.astype(np.float64).reshape(b, sq, hkv, g, d)
    logits = np.einsum("bqhgd,bkhd->bqhgk", qf,
                       k.astype(np.float64)) / math.sqrt(d)
    if mask is not None:
        logits = np.where(mask[None, :, None, None, :], logits, -np.inf)
    logits -= logits.max(-1, keepdims=True)
    p = np.exp(logits)
    p /= p.sum(-1, keepdims=True)
    out = np.einsum("bqhgk,bkhd->bqhgd", p, v.astype(np.float64))
    return out.reshape(b, sq, hq, d)


def _rand(rng, shape, dtype=np.float32):
    return jnp.asarray(rng.standard_normal(shape).astype(np.float32), dtype)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)], ids=["mha", "gqa"])
def test_flash_multi_tile_vs_dense(causal, hq, hkv):
    """Several q AND k tiles (tq=128, tk=128) — the real grid walk."""
    b, sq, sk, d = 2, 256, 384, 32
    rng = np.random.default_rng(0)
    q = _rand(rng, (b, sq, hq, d))
    k = _rand(rng, (b, sk, hkv, d))
    v = _rand(rng, (b, sk, hkv, d))
    mask = ((np.arange(sq)[:, None] >= np.arange(sk)[None, :])
            if causal else None)
    gold = _dense(np.asarray(q), np.asarray(k), np.asarray(v), mask)
    out = flash_attention(q, k, v, causal=causal, tile_q=128, tile_k=128)
    np.testing.assert_allclose(np.asarray(out), gold, rtol=2e-4, atol=2e-4)


def test_flash_partial_matches_block_attn():
    """(acc, m, l) contract equals the dense partial, with rank offsets."""
    b, sq, sk, hq, hkv, d = 1, 128, 128, 4, 2, 32
    rng = np.random.default_rng(1)
    q = _rand(rng, (b, sq, hq, d))
    k = _rand(rng, (b, sk, hkv, d))
    v = _rand(rng, (b, sk, hkv, d))
    q_off, k_off = 256, 128   # rank-2 queries over rank-1 keys
    mask = ((np.arange(sq) + q_off)[:, None]
            >= (np.arange(sk) + k_off)[None, :])
    acc_g, m_g, l_g = _block_attn(q, k, v, jnp.asarray(mask))
    acc, m, l = flash_attention_partial(q, k, v, q_offset=q_off,
                                        k_offset=k_off)
    np.testing.assert_allclose(np.asarray(m), np.asarray(m_g), atol=1e-5)
    np.testing.assert_allclose(np.asarray(l), np.asarray(l_g), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(acc), np.asarray(acc_g),
                               rtol=2e-3, atol=2e-3)


def test_flash_shard_merge_equals_full():
    """Two shards merged via _merge == one full-sequence attention."""
    b, s, hq, hkv, d = 1, 256, 4, 2, 32
    rng = np.random.default_rng(2)
    q = _rand(rng, (b, s, hq, d))
    k = _rand(rng, (b, s, hkv, d))
    v = _rand(rng, (b, s, hkv, d))
    half = s // 2
    # Queries are the SECOND half of the sequence (positions half..s).
    q2 = q[:, half:]
    p1 = flash_attention_partial(q2, k[:, :half], v[:, :half],
                                 q_offset=half, k_offset=0)
    p2 = flash_attention_partial(q2, k[:, half:], v[:, half:],
                                 q_offset=half, k_offset=half)
    acc, m, l = _merge(p1, p2)
    merged = acc / np.maximum(np.asarray(l), 1e-30)[..., None]
    mask = np.tril(np.ones((s, s), bool))[half:]
    gold = _dense(np.asarray(q2), np.asarray(k), np.asarray(v), mask)
    np.testing.assert_allclose(np.asarray(merged), gold, rtol=2e-4,
                               atol=2e-4)


def test_flash_hidden_shard_is_dead():
    """A shard entirely ahead of the queries returns l == 0 (skipped)."""
    rng = np.random.default_rng(3)
    q = _rand(rng, (1, 128, 4, 32))
    k = _rand(rng, (1, 128, 4, 32))
    v = _rand(rng, (1, 128, 4, 32))
    _, _, l = flash_attention_partial(q, k, v, q_offset=0, k_offset=4096)
    assert float(jnp.max(l)) == 0.0


def test_flash_bf16():
    b, s, hq, hkv, d = 1, 256, 4, 2, 64
    rng = np.random.default_rng(4)
    q = _rand(rng, (b, s, hq, d), jnp.bfloat16)
    k = _rand(rng, (b, s, hkv, d), jnp.bfloat16)
    v = _rand(rng, (b, s, hkv, d), jnp.bfloat16)
    gold = _dense(np.asarray(q, np.float32), np.asarray(k, np.float32),
                  np.asarray(v, np.float32), np.tril(np.ones((s, s), bool)))
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32), gold,
                               rtol=2e-2, atol=2e-2)


def test_dispatcher_fallback_on_odd_shapes():
    """A sequence with no 128-aligned divisor forces whole-dim tiles past
    the VMEM budget; flash_supported refuses and the dispatcher answers
    through the dense path."""
    rng = np.random.default_rng(5)
    q = _rand(rng, (1, 997, 2, 128))   # prime S, d=128 -> tile = S, too big
    k = _rand(rng, (1, 997, 1, 128))
    v = _rand(rng, (1, 997, 1, 128))
    assert not flash_supported(q, k)
    out = shard_attention(q, k, v, causal=True)
    gold = _dense(np.asarray(q), np.asarray(k), np.asarray(v),
                  np.tril(np.ones((997, 997), bool)))
    np.testing.assert_allclose(np.asarray(out), gold, rtol=2e-4, atol=2e-4)
    acc, m, l = shard_attention_partial(q, k, v, q_offset=997, k_offset=0)
    assert acc.shape == (1, 997, 2, 128)


def test_flash_supported_rejects_vmem_blowup():
    """A sequence with no 128-aligned divisor forces a whole-dim tile; the
    predicate must refuse once that blows the VMEM budget."""
    q = jnp.zeros((1, 9973, 4, 128))      # prime S -> tile == S
    k = jnp.zeros((1, 9973, 2, 128))
    assert not flash_supported(q, k)


def test_dense_fallback_traced_offsets():
    """The dense fallback must honor TRACED positional offsets (the chunked
    prefill contract: a fori_loop chunk body passes traced starts even when
    the shape routes to the dense path)."""
    import jax

    b, s, hq, hkv, d = 1, 33, 2, 1, 32   # odd S -> whole-dim tiles, tiny
    rng = np.random.default_rng(7)
    q = _rand(rng, (b, s, hq, d))
    k = _rand(rng, (b, 2 * s, hkv, d))
    v = _rand(rng, (b, 2 * s, hkv, d))

    @jax.jit
    def run(q, k, v, off):
        acc, m, l = shard_attention_partial(q, k, v, q_offset=off,
                                            k_offset=0, causal=True)
        return acc / jnp.maximum(l, 1e-30)[..., None]

    out = run(q, k, v, jnp.int32(33))
    mask = (np.arange(s) + 33)[:, None] >= np.arange(2 * s)[None, :]
    gold = _dense(np.asarray(q), np.asarray(k), np.asarray(v), mask)
    np.testing.assert_allclose(np.asarray(out), gold, rtol=2e-4, atol=2e-4)
