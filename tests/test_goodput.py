"""Goodput observatory (ISSUE 19, docs/observability.md "Goodput &
waste attribution").

The load-bearing contracts: the work ledger attributes every dispatched
device token-row to exactly one category (useful / spec_rejected /
recompute / overhead / idle) with dispatch widths recorded SEPARATELY
from the attribution, so the PARTITION INVARIANT (Σ categories == rows)
is a real cross-check on the instrumentation; records are
byte-deterministic under the loop's injected clock; per-request waste
counters reconcile exactly with the ledger lanes; the interval sampler
and windowed alert rules fire ``goodput_regression`` flight dumps
through the established trigger chain; and ``obs.report --check``
gates both the lane and the partition on every dumped record.
"""

import json
import os

import pytest

import jax

from triton_distributed_tpu import obs
from triton_distributed_tpu.models.config import tiny_config
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.obs import flight as obs_flight
from triton_distributed_tpu.obs import goodput
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.obs import postmortem as obs_postmortem
from triton_distributed_tpu.obs import report as obs_report
from triton_distributed_tpu.obs import stepprof
from triton_distributed_tpu.obs import trace as obs_trace
from triton_distributed_tpu.obs.goodput import WorkLedger
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving.loadgen import (
    LoadSpec, build_trace, run_trace,
)
from triton_distributed_tpu.serving.loop import ServingEngine
from triton_distributed_tpu.serving.spec import (
    SpecConfigError, attribute_verify_rows,
)


@pytest.fixture(autouse=True)
def _no_leaked_observers():
    goodput.disable()
    stepprof.disable()
    obs_trace.disable()
    yield
    goodput.disable()
    stepprof.disable()
    obs_trace.disable()


@pytest.fixture(scope="module")
def ctx1():
    return initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])


@pytest.fixture(scope="module")
def served(ctx1):
    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(7), cfg)
    return Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                  page_size=4)


class CounterClock:
    """Deterministic injectable clock: monotone, no wall time."""

    def __init__(self, step: float = 0.001):
        self.t = 0.0
        self.step = step

    def __call__(self) -> float:
        self.t += self.step
        return round(self.t, 6)


def _assert_partition(recs):
    assert recs, "no work records produced"
    for rec in recs:
        problem = goodput.check_partition(rec)
        assert problem is None, problem


def _ledgered_run(eng, trace, **kw):
    """One serving replay under a private ledger + CounterClock;
    returns (ledger, report)."""
    gl = WorkLedger(interval=2)
    prev = goodput.set_ledger(gl)
    try:
        se = ServingEngine(eng, clock=CounterClock(), **kw)
        report = run_trace(se, [dict(t) for t in trace])
    finally:
        goodput.set_ledger(prev)
    return gl, report


# ---------------------------------------------------------------------------
# The partition contract (unit level).
# ---------------------------------------------------------------------------

def test_check_partition_rejects_broken_records():
    good = {"it": 3, "rows": 10, "work": {"useful": 7, "idle": 3},
            "goodput_frac": 0.7, "prefill_saved": 2}
    assert goodput.check_partition(good) is None
    assert "partition invariant broken" in goodput.check_partition(
        {**good, "work": {"useful": 7}})
    assert "missing 'work'" in goodput.check_partition({"rows": 1})
    assert "unknown work category" in goodput.check_partition(
        {**good, "work": {"useful": 9, "cache_miss": 1}})
    # Exact integer discipline: bools and floats are not row counts.
    assert goodput.check_partition({**good, "rows": True}) is not None
    assert "non-int/negative" in goodput.check_partition(
        {**good, "work": {"useful": 7.0, "idle": 3}})
    assert "outside [0, 1]" in goodput.check_partition(
        {**good, "goodput_frac": 1.7})
    assert "prefill_saved" in goodput.check_partition(
        {**good, "prefill_saved": -1})
    # The flight ride-along shape (no it/frac) is also checkable.
    assert goodput.check_partition(
        {"rows": 4, "work": {"useful": 4}}) is None


def test_attribute_verify_rows_partitions_by_construction():
    """The verify-launch split rule lives next to the acceptance rule
    it mirrors: accepted → useful, live-but-rejected → spec_rejected,
    padding → idle, and Σ == rows dispatched."""
    out = attribute_verify_rows(8, wins=[3, 3], accepted=[2, 1])
    assert out == {"useful": 3, "spec_rejected": 3, "idle": 2}
    assert sum(out.values()) == 8
    # Whole-batch padding (no live slots) is all idle.
    assert attribute_verify_rows(4, wins=[], accepted=[]) == {
        "useful": 0, "spec_rejected": 0, "idle": 4}
    with pytest.raises(SpecConfigError):
        attribute_verify_rows(8, wins=[3], accepted=[4])   # acc > live
    with pytest.raises(SpecConfigError):
        attribute_verify_rows(2, wins=[3], accepted=[1])   # live > rows


def test_workledger_lifecycle_and_record_shape():
    gl = WorkLedger(interval=100)
    assert not gl.active()
    # Hooks are no-ops without an open iteration — the instrumentation
    # sites fire unconditionally on the serving hot path.
    gl.dispatch(5)
    gl.add("useful", 5)
    gl.credit_saved(2)
    assert not gl.has_records() and gl.cumulative() == {}
    gl.begin_iteration(0, 1.0, clock=CounterClock())
    gl.dispatch(10)
    gl.add("useful", 6)
    gl.add("idle", 3)
    gl.add("recompute", 1)
    gl.add("overhead", 0)            # zero rows: category stays absent
    gl.credit_saved(4)
    with pytest.raises(ValueError):
        gl.add("cache_miss", 1)      # taxonomy is closed
    rec = gl.finish_iteration(2.0)
    assert rec["rows"] == 10
    assert rec["work"] == {"useful": 6, "recompute": 1, "idle": 3}
    assert rec["goodput_frac"] == 0.6
    assert rec["prefill_saved"] == 4
    assert rec["rows_cum"] == 10 and rec["goodput_frac_cum"] == 0.6
    assert goodput.check_partition(rec) is None
    # A crashed iteration never reached finish — the next begin closes
    # it as aborted so the ring stays one partition per record.
    gl.begin_iteration(1, 3.0)
    gl.dispatch(2)
    gl.add("useful", 2)
    gl.begin_iteration(2, 4.0)
    gl.finish_iteration(5.0)
    recs = gl.records()
    assert [r["it"] for r in recs] == [0, 1, 2]
    assert recs[1]["aborted"] is True
    _assert_partition(recs)
    cum = gl.cumulative()
    assert cum["rows"] == 12 and cum["prefill_saved"] == 4
    assert gl.goodput_frac() == round(8 / 12, 6)
    assert gl.cumulative_all()["rows"] == 12
    # Empty-dispatch iterations are vacuously all-useful, not 0-goodput.
    assert recs[2]["rows"] == 0 and recs[2]["goodput_frac"] == 1.0


def test_env_knobs_configure_sampler(monkeypatch):
    monkeypatch.setenv("TDTPU_GOODPUT_INTERVAL", "2")
    monkeypatch.setenv("TDTPU_GOODPUT_WINDOW", "5")
    monkeypatch.setenv("TDTPU_GOODPUT_FLOOR", "0.75")
    monkeypatch.setenv("TDTPU_GOODPUT_WASTE_MAX", "0.4")
    gl = WorkLedger()
    assert (gl.interval, gl.window) == (2, 5)
    assert gl.goodput_floor == 0.75 and gl.waste_ceiling == 0.4
    # Explicit kwargs beat the environment.
    gl2 = WorkLedger(interval=7, window=1)
    assert (gl2.interval, gl2.window) == (7, 1)


# ---------------------------------------------------------------------------
# Interval time-series + windowed alert rules (unit level).
# ---------------------------------------------------------------------------

def _iterate(gl, useful, waste_cat=None, waste=0):
    it = len(gl.records())
    gl.begin_iteration(it, float(it))
    gl.dispatch(useful + waste)
    gl.add("useful", useful)
    if waste_cat is not None and waste:
        gl.add(waste_cat, waste)
    gl.finish_iteration(float(it) + 0.5)


def test_floor_rule_needs_window_consecutive_breaches():
    """goodput below the floor fires only after ``window`` consecutive
    breaching samples; an idle (rows == 0) sample resets the streak,
    and the streak resets after firing."""
    gl = WorkLedger(interval=1, window=2, goodput_floor=0.9)
    _iterate(gl, useful=1, waste_cat="idle", waste=9)   # 0.1 — breach 1
    assert gl.alerts == []
    _iterate(gl, useful=0)                              # idle: reset
    _iterate(gl, useful=1, waste_cat="idle", waste=9)   # breach 1
    assert gl.alerts == []
    _iterate(gl, useful=1, waste_cat="idle", waste=9)   # breach 2: fire
    assert [a["rule"] for a in gl.alerts] == ["goodput_floor"]
    assert "below" in gl.alerts[0]["reason"]
    _iterate(gl, useful=1, waste_cat="idle", waste=9)   # post-fire: 1
    assert len(gl.alerts) == 1, "streak must reset after firing"
    # The loop drains pending alerts exactly once.
    assert [a["rule"] for a in gl.consume_alerts()] == ["goodput_floor"]
    assert gl.consume_alerts() == []
    tl = gl.timeline()
    assert tl["schema"] == "tdtpu-goodput-timeline-v1"
    assert len(tl["samples"]) == 5 and len(tl["alerts"]) == 1


def test_waste_spike_rule_is_per_category():
    gl = WorkLedger(interval=1, window=1, waste_ceiling=0.3)
    _iterate(gl, useful=5, waste_cat="recompute", waste=5)   # 0.5 > 0.3
    _iterate(gl, useful=9, waste_cat="spec_rejected", waste=1)  # 0.1 ok
    assert [a["rule"] for a in gl.alerts] == ["waste_spike:recompute"]
    # Both rule families can watch the same sample stream.
    gl2 = WorkLedger(interval=1, window=1, goodput_floor=0.9,
                     waste_ceiling=0.3)
    _iterate(gl2, useful=1, waste_cat="recompute", waste=9)
    assert sorted(a["rule"] for a in gl2.alerts) == [
        "goodput_floor", "waste_spike:recompute"]


# ---------------------------------------------------------------------------
# Serving tiers: partition, determinism, reconciliation.
# ---------------------------------------------------------------------------

def test_dense_decode_partitions_and_is_byte_deterministic(served):
    """Two identically-seeded replays under the injected clock produce
    BYTE-IDENTICAL work records; every record satisfies the partition
    invariant and padding lands in ``idle``."""
    trace = build_trace(LoadSpec(n_requests=2, seed=3,
                                 prompt_len=(4, 4), max_new=(3, 3),
                                 mean_interarrival_iters=0.0))
    gl1, report = _ledgered_run(served, trace, max_batch=4,
                                num_pages=16, prefill_chunk=4)
    gl2, _ = _ledgered_run(served, trace, max_batch=4,
                           num_pages=16, prefill_chunk=4)
    assert report["all_finished"]
    recs = gl1.records()
    _assert_partition(recs)
    assert json.dumps(recs, sort_keys=True) == \
        json.dumps(gl2.records(), sort_keys=True), \
        "work records are not byte-deterministic under a fake clock"
    cum = gl1.cumulative()
    assert cum.get("useful", 0) > 0
    assert cum.get("idle", 0) > 0, \
        "2 requests in a max_batch=4 step must charge padding to idle"
    assert cum["rows"] == sum(r["rows"] for r in recs)
    # Cumulative fraction on the last record matches the lane totals.
    assert recs[-1]["goodput_frac_cum"] == gl1.goodput_frac()


def test_preemption_charges_recompute_and_reconciles(served):
    """Page pressure forces eviction mid-decode: the re-prefill of
    already-computed positions lands in ``recompute`` (via the
    request's computed_high high-water mark) and Σ per-request
    ``recompute_tokens`` reconciles EXACTLY with the ledger lane."""
    trace = build_trace(LoadSpec(n_requests=8, seed=0,
                                 mean_interarrival_iters=1.0))
    gl, report = _ledgered_run(served, trace, max_batch=4, num_pages=8,
                               prefill_chunk=4, max_waiting=8)
    assert report["all_finished"]
    assert report["preemptions"] > 0, \
        "pool sizing no longer exercises eviction"
    _assert_partition(gl.records())
    cum = gl.cumulative()
    assert cum.get("recompute", 0) > 0, \
        "preempted resumes never charged the recompute lane"
    reqs = report["requests"]
    assert sum(r.recompute_tokens for r in reqs) == cum["recompute"]
    assert sum(r.wasted_tokens for r in reqs) == \
        cum["recompute"] + cum.get("spec_rejected", 0)


def test_spec_rejection_attributed_and_reconciled(served):
    """Draft-and-verify: rejected candidate rows land in
    ``spec_rejected`` and reconcile with per-request rejected_tokens;
    the verify launch's split keeps the partition."""
    prompts = [[3, 9] * 4, [7, 7, 7, 7, 7], [11, 4, 11, 4, 11, 4]]
    trace = [{"req_id": f"sp-{i}", "arrival_iter": 0, "prompt": p,
              "max_new_tokens": g, "priority": 0}
             for i, (p, g) in enumerate(zip(prompts, [10, 8, 8]))]
    gl, report = _ledgered_run(served, trace, max_batch=3,
                               num_pages=24, prefill_chunk=4, spec_k=2)
    assert report["all_finished"]
    _assert_partition(gl.records())
    cum = gl.cumulative()
    assert cum.get("spec_rejected", 0) > 0, \
        "no verify launch rejected a candidate row"
    reqs = report["requests"]
    assert sum(r.rejected_tokens for r in reqs) == cum["spec_rejected"]


def test_warm_prefix_admission_credits_prefill_saved(served):
    """A warm prefix-cache admission skips the covered prefix rows:
    they were never dispatched, so they land in the ``prefill_saved``
    credit OUTSIDE the partition — not in any category."""
    gl = WorkLedger(interval=2)
    prev = goodput.set_ledger(gl)
    try:
        se = ServingEngine(served, max_batch=2, num_pages=16,
                           prefill_chunk=4, prefix_cache=True,
                           clock=CounterClock())
        pre = list(range(10, 22))
        se.submit(pre + [3, 5, 8, 9], 4, req_id="cold")
        se.run()
        saved_cold = gl.cumulative().get("prefill_saved", 0)
        se.submit(pre + [3, 5, 8, 30, 31, 32], 4, req_id="warm")
        se.run()
    finally:
        goodput.set_ledger(prev)
    _assert_partition(gl.records())
    cum = gl.cumulative()
    assert saved_cold == 0, "a cold admission must not claim the credit"
    assert cum["prefill_saved"] > 0, \
        "the warm admission never credited prefill_saved"
    # The credit is visible on the admitting iteration's record.
    assert any(r["prefill_saved"] > 0 for r in gl.records())


def test_fleet_replica_lanes_and_run_artifacts(tmp_path):
    """Fleet replicas step through ONE ledger: records carry replica
    labels, per-lane cumulative totals stay separate, the router
    publishes the fleet-mean gauge + replica-labeled variants, and
    ``obs.finish_run`` lands goodput.spans.json + timeline.json."""
    from triton_distributed_tpu.fleet import FleetRouter, ReplicaHandle

    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(7), cfg)
    reps = []
    for i in range(2):
        ctx = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                     devices=jax.devices()[:1])
        eng = Engine(cfg, params, ctx, backend="xla", max_seq=64,
                     page_size=4)
        reps.append(ReplicaHandle.build(str(i), eng, max_batch=2,
                                        num_pages=16, prefill_chunk=4,
                                        max_waiting=8))
    obs.start_run(str(tmp_path))
    try:
        router = FleetRouter(reps, policy="round_robin")
        run_trace(router, build_trace(LoadSpec(
            n_requests=4, seed=5, prompt_len=(4, 6), max_new=(3, 4),
            mean_interarrival_iters=0.0)))
        gl = goodput.get_ledger()
        recs = gl.records()
        labels = sorted({r.get("replica") for r in recs} - {None})
        cum0, cum1 = gl.cumulative("0"), gl.cumulative("1")
        snap = obs_metrics.registry().snapshot()
    finally:
        obs.finish_run()
    _assert_partition(recs)
    assert labels == ["0", "1"], \
        f"per-replica attribution lost (labels {labels})"
    assert cum0.get("rows", 0) > 0 and cum1.get("rows", 0) > 0
    total = gl.cumulative_all()
    assert total["rows"] == cum0["rows"] + cum1["rows"]
    merged = snap.get(obs_metrics.SERVE_GOODPUT_FRAC)
    assert merged is not None and 0.0 < merged["value"] <= 1.0
    labeled = [k for k in snap
               if k.startswith(obs_metrics.SERVE_GOODPUT_FRAC + "{")
               and 'replica="' in k]
    assert len(labeled) == 2, labeled
    # The run dir carries both artifacts with per-replica lanes.
    spans = json.load(open(tmp_path / "goodput.spans.json"))
    counters = {e["name"] for e in spans["traceEvents"]
                if e.get("ph") == "C"}
    assert {"work_tokens/0", "work_tokens/1", "goodput_frac/0",
            "goodput_frac/1"} <= counters
    tl = json.load(open(tmp_path / "timeline.json"))
    assert set(tl["cumulative"]) == {"0", "1"}


# ---------------------------------------------------------------------------
# Evidence surfaces: flight dump, postmortem, report gate.
# ---------------------------------------------------------------------------

def test_floor_breach_fires_goodput_regression_flight_dump(served,
                                                           tmp_path):
    """A seeded goodput-floor breach fires the windowed rule, the loop
    dumps through the ``goodput_regression`` trigger kind, the dumped
    records carry partition-valid work dicts, the postmortem renders
    the goodput table, and ``obs.report --check`` validates it all."""
    from triton_distributed_tpu.obs.slo import SLOConfig

    prior = obs_metrics.set_registry(obs_metrics.Registry())
    gl = WorkLedger(interval=1, window=1, goodput_floor=0.99)
    prev = goodput.set_ledger(gl)
    os.environ["TDTPU_FLIGHT_DIR"] = str(tmp_path)
    try:
        # The default SLO config turns on the observability path (flight
        # iteration records) without arming any violation rule.
        se = ServingEngine(served, max_batch=4, num_pages=16,
                           prefill_chunk=4, slo_cfg=SLOConfig(),
                           clock=CounterClock())
        se.submit(list(range(1, 8)), 3, req_id="fb-0")
        se.run()
    finally:
        os.environ.pop("TDTPU_FLIGHT_DIR", None)
        goodput.set_ledger(prev)
        obs_metrics.set_registry(prior)
    assert gl.alerts, "padding below a 0.99 floor must breach"
    dumps = [p for p in obs_flight.find_dumps(str(tmp_path))
             if "goodput_regression" in os.path.basename(p)]
    assert dumps, "no goodput_regression dump was written"
    data = obs_flight.load_dump(dumps[0])
    assert data["trigger"]["kind"] == "goodput_regression"
    assert "goodput_floor" in data["trigger"]["reason"]
    ledgered = [r for r in data["iterations"]
                if isinstance(r.get("goodput"), dict)]
    assert ledgered, "flight records carry no work dicts"
    for rec in ledgered:
        assert goodput.check_partition(rec["goodput"]) is None
    rendered = obs_postmortem.render(data, dumps[0])
    assert "goodput (token-rows; good% = useful/rows):" in rendered
    assert "cumulative goodput_frac:" in rendered
    assert obs_report.main([str(tmp_path), "--check", "--require-series",
                            "", "--allow-missing-step-profile"]) == 0
    # The machine-readable postmortem carries the per-dump aggregate.
    out = str(tmp_path / "pm.json")
    assert obs_postmortem.main([str(tmp_path), "--check", "--json", out,
                                "--quiet"]) == 0
    pm = json.load(open(out))
    assert pm["ok"] and pm["problems"] == []
    entry = next(e for e in pm["dumps"]
                 if e["trigger_detail"]["kind"] == "goodput_regression")
    agg = entry["goodput"]
    assert agg["partition_ok"] and agg["rows"] > 0
    assert agg["rows"] == sum(agg["work"].values())
    assert entry["valid"]


def test_report_check_gates_goodput_lane_and_partition(tmp_path):
    """A serving-tier snapshot without the goodput lane fails --check
    (waste attribution lost); the opt-out or the lane passes it; a
    flight dump whose work dict breaks the partition invariant fails
    --check even with the lane present."""
    from triton_distributed_tpu.obs.reqtrace import ReqTracer
    from triton_distributed_tpu.obs.stepprof import StepProfiler

    reg = obs_metrics.Registry()
    reg.counter(obs_metrics.SERVE_FINISHED, "x").inc(1)
    reg.gauge(obs_metrics.KV_PAGES_RESIDENT, "x").set(4)
    reg.save(str(tmp_path))
    rt = ReqTracer()
    rt.arrival("r-0", 0.0)
    rt.save(str(tmp_path / "requests.spans.json"))
    sp = StepProfiler()
    sp.begin_iteration(0, 1.0)
    sp.finish_iteration(1.5)
    sp.save(str(tmp_path / "steps.spans.json"))
    # The KV host-tier lane (ISSUE 20) gates the same way; opt out so
    # this test stays focused on the goodput lane.
    args = [str(tmp_path), "--check", "--require-series", "",
            "--allow-missing-kv-tier"]
    assert obs_report.main(args) == 1
    assert obs_report.main(args + ["--allow-missing-goodput"]) == 0
    gl = WorkLedger(interval=1)
    gl.begin_iteration(0, 1.0)
    gl.dispatch(4)
    gl.add("useful", 4)
    gl.finish_iteration(2.0)
    gl.save(str(tmp_path / "goodput.spans.json"))
    gl.save_timeline(str(tmp_path / "timeline.json"))
    assert obs_report.main(args) == 0
    # Now a flight dump whose work dict breaks the partition.
    rec = obs_flight.FlightRecorder(capacity=4, run_dir=str(tmp_path))
    rec.record({"iter": 0,
                "goodput": {"rows": 5, "work": {"useful": 3}}})
    rec.dump("slo_violation", "synthetic partition break", 1)
    assert obs_report.main(args) == 1
