"""AOT compile + dispatch tests (L11 analog; reference
test/nvidia/test_compile_aot.py pattern: compile a space offline, dispatch by
runtime signature, golden-check results)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.tools.aot import (
    AOTFunction, aot_compile_spaces, signature_key,
)


def _scale(x, *, factor=2.0):
    return x * factor


def test_precompile_exact_dispatch():
    af = AOTFunction(_scale, "scale")
    spec = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    af.precompile(spec)
    x = jnp.ones((8, 16), jnp.float32)
    np.testing.assert_allclose(np.asarray(af(x)), 2.0 * np.ones((8, 16)))
    # Unknown signature without fallback raises.
    with pytest.raises(KeyError):
        af(jnp.ones((4, 4), jnp.float32))


def test_jit_fallback_cached():
    af = AOTFunction(_scale, "scale", allow_jit_fallback=True)
    x = jnp.ones((4, 4), jnp.float32)
    np.testing.assert_allclose(np.asarray(af(x, factor=3.0)), 3.0)
    assert len(af._jit_fallbacks) == 1
    np.testing.assert_allclose(np.asarray(af(x, factor=3.0)), 3.0)
    assert len(af._jit_fallbacks) == 1  # reused, not rebuilt


def test_bucket_dispatch():
    """Flash-decode pattern: pick the smallest compiled M >= runtime M."""
    af = AOTFunction(_scale, "scale")
    for m in (128, 512):
        af.precompile(jax.ShapeDtypeStruct((m, 16), jnp.float32),
                      bucket=(0, 0))
    probe = jnp.ones((200, 16), jnp.float32)
    entry = af.select_bucket(probe, bucket=(0, 0))
    assert entry is not None and entry.bucket == 512
    padded = jnp.zeros((entry.bucket, 16), jnp.float32).at[:200].set(probe)
    out = entry.compiled(padded)[:200]
    np.testing.assert_allclose(np.asarray(out), 2.0)
    # Larger than every bucket -> no entry.
    assert af.select_bucket(jnp.ones((1024, 16), jnp.float32),
                            bucket=(0, 0)) is None


def test_save_load_roundtrip(tmp_path):
    af = AOTFunction(_scale, "scale")
    spec = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    af.precompile(spec, static_kwargs={"factor": 4.0})
    n = af.save(str(tmp_path))
    assert n == 1  # XLA-only fn serializes via jax.export on every backend
    loaded = AOTFunction.load(str(tmp_path), fn=_scale)
    x = jnp.ones((8, 16), jnp.float32)
    np.testing.assert_allclose(np.asarray(loaded(x, factor=4.0)), 4.0)


def test_save_manifest_with_dtype_static_kwarg(tmp_path):
    """Regression: non-JSON static kwargs that signature_key accepts must not
    crash save() (it now uses the same default=str encoding)."""

    def cast(x, *, dtype=jnp.float32):
        return x.astype(dtype)

    af = AOTFunction(cast, "cast")
    af.precompile(jax.ShapeDtypeStruct((8, 16), jnp.float32),
                  static_kwargs={"dtype": jnp.bfloat16})
    af.save(str(tmp_path))
    assert (tmp_path / "manifest.json").exists()
    # The coerced-to-string kwargs must never be recompiled into fn: a
    # serialized artifact reloads fine, but a hypothetical process-local
    # entry would be skipped (static_kwargs_portable=False in the manifest).
    import json
    manifest = json.loads((tmp_path / "manifest.json").read_text())
    assert manifest["entries"][0]["static_kwargs_portable"] is False
    loaded = AOTFunction.load(str(tmp_path), fn=cast)
    x = jnp.ones((8, 16), jnp.float32)
    if loaded.entries:  # reloaded from the serialized artifact
        out = loaded(x, dtype=jnp.bfloat16)
        assert out.dtype == jnp.bfloat16


def test_aot_compile_spaces_decorator():
    @aot_compile_spaces([
        {"args": (jax.ShapeDtypeStruct((8, 8), jnp.float32),)},
        {"args": (jax.ShapeDtypeStruct((16, 8), jnp.float32),),
         "bucket": (0, 0)},
    ], name="scale_space")
    def scale(x, *, factor=2.0):
        return x * factor

    af = scale.build()
    assert af.registry.size() >= 2
    np.testing.assert_allclose(
        np.asarray(af(jnp.ones((8, 8), jnp.float32))), 2.0)


def test_signature_key_stable():
    a = jnp.ones((8, 16), jnp.bfloat16)
    assert signature_key([a]) == "bfloat16[8,16]"
    assert signature_key([a], {"z": 1}) != signature_key([a])
