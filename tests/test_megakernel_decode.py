"""Full decode-step megakernel vs straight-jax golden (reference
mega_triton_kernel/test/test_qwen3.py role: assemble the model path, run
the single launch, compare against the eager implementation)."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.megakernel.models import (
    broadcast_rows, build_decode_step, feed_layer_weights, rope_tables,
)
from triton_distributed_tpu.megakernel.tasks import TILE
from triton_distributed_tpu.runtime import shard_map_on


def _j(v):
    """asarray that passes (gate, up) pair-feed tuples through."""
    return (tuple(jnp.asarray(x) for x in v) if isinstance(v, tuple)
            else jnp.asarray(v))


def _golden_layer(x, w, pos, kT, v, hq, hkv, eps=1e-6):
    """Eager numpy/jax implementation of exactly the assembled math."""
    d = TILE

    def rms(a, g):
        return (a / np.sqrt((a ** 2).mean(-1, keepdims=True) + eps)) * g

    def rope(a, cos_h, sin_h):
        a1, a2 = a[:, :d // 2], a[:, d // 2:]
        return np.concatenate([a1 * cos_h - a2 * sin_h,
                               a2 * cos_h + a1 * sin_h], axis=1)

    cos_h, sin_h = w["cos_h"], w["sin_h"]
    xn = rms(x, w["attn_norm"])
    q = xn @ w["wq"]
    k_new = xn @ w["wk"]
    v_new = xn @ w["wv"]
    groups = hq // hkv
    attn = np.zeros_like(q)
    for j in range(hq):
        kv = j // groups
        qj = rope(rms(q[:, j * d:(j + 1) * d], w["q_norm"]), cos_h, sin_h)
        kj = rope(rms(k_new[:, kv * d:(kv + 1) * d], w["k_norm"]), cos_h,
                  sin_h)
        vj = v_new[:, kv * d:(kv + 1) * d]
        # scores over cache[:pos] + the current token (per batch row).
        s_cache = (qj @ kT[kv][:, :pos]) * d ** -0.5        # (B, pos)
        s_cur = (qj * kj).sum(-1, keepdims=True) * d ** -0.5
        s = np.concatenate([s_cache, s_cur], axis=1)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        attn[:, j * d:(j + 1) * d] = (
            p[:, :pos] @ v[kv][:pos] + p[:, pos:] * vj)
    x1 = x + attn @ w["wo"]
    x1n = rms(x1, w["mlp_norm"])
    g = x1n @ w["w_gate"]
    act = g / (1 + np.exp(-g)) * (x1n @ w["w_up"])
    return x1 + act @ w["w_down"]


def _rand_layer_weights(rng, hidden, hq, hkv, ffn, pos):
    d = TILE
    cos_full, sin_full = rope_tables(pos, d, 1e6)
    return {
        "attn_norm": rng.standard_normal(hidden).astype(np.float32) * 0.1 + 1,
        "mlp_norm": rng.standard_normal(hidden).astype(np.float32) * 0.1 + 1,
        "q_norm": rng.standard_normal(d).astype(np.float32) * 0.1 + 1,
        "k_norm": rng.standard_normal(d).astype(np.float32) * 0.1 + 1,
        "wq": rng.standard_normal((hidden, hq * d)).astype(np.float32) * 0.05,
        "wk": rng.standard_normal((hidden, hkv * d)).astype(np.float32) * 0.05,
        "wv": rng.standard_normal((hidden, hkv * d)).astype(np.float32) * 0.05,
        "wo": rng.standard_normal((hq * d, hidden)).astype(np.float32) * 0.05,
        "w_gate": rng.standard_normal((hidden, ffn)).astype(np.float32) * 0.05,
        "w_up": rng.standard_normal((hidden, ffn)).astype(np.float32) * 0.05,
        "w_down": rng.standard_normal((ffn, hidden)).astype(np.float32) * 0.05,
        "cos_full": cos_full, "sin_full": sin_full,
        "cos_h": cos_full[0, :d // 2], "sin_h": sin_full[0, :d // 2],
    }


def _feed_layer(prog, h, w, kT_np, v_np):
    feeds = {
        h.attn_norm: broadcast_rows(w["attn_norm"]),
        h.mlp_norm: broadcast_rows(w["mlp_norm"]),
        h.q_norm: broadcast_rows(w["q_norm"]),
        h.k_norm: broadcast_rows(w["k_norm"]),
    }
    feed_layer_weights(feeds, h, wq=w["wq"], wk=w["wk"], wv=w["wv"],
                       wo=w["wo"], w_gate=w["w_gate"], w_up=w["w_up"],
                       w_down=w["w_down"])
    for i, (tk, tv) in enumerate(zip(h.kT, h.v)):
        feeds[tk] = kT_np[i]
        feeds[tv] = v_np[i]
    return feeds


def test_decode_step_single_device():
    hidden, hq, hkv, ffn, S, pos, B = 256, 2, 1, 256, 256, 100, 4
    rng = np.random.default_rng(0)
    prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                             ffn_local=ffn, num_layers=1, max_seq=S,
                             pos=pos, num_ranks=1)
    w = _rand_layer_weights(rng, hidden, hq, hkv, ffn, pos)
    kT_np = [rng.standard_normal((TILE, S)).astype(np.float32) * 0.3
             for _ in range(hkv)]
    v_np = [rng.standard_normal((S, TILE)).astype(np.float32) * 0.3
            for _ in range(hkv)]
    x = np.zeros((TILE, hidden), np.float32)
    x[:B] = rng.standard_normal((B, hidden)).astype(np.float32) * 0.3

    compiled = prog.mb.compile()
    feeds = {prog.x: jnp.asarray(x), prog.cos: jnp.asarray(w["cos_full"]),
             prog.sin: jnp.asarray(w["sin_full"])}
    feeds.update({k: _j(val) for k, val in
                  _feed_layer(prog, prog.layers[0], w, kT_np, v_np).items()})
    out, k_new, v_new = compiled.run(
        feeds, outputs=[prog.x_out, prog.layers[0].k_new,
                        prog.layers[0].v_new])

    ref = _golden_layer(x[:B], w, pos, kT_np, v_np, hq, hkv)
    np.testing.assert_allclose(np.asarray(out)[:B], ref, rtol=2e-3, atol=2e-3)

    # The step also emits this position's k/v for the host-side cache append
    # (pre-norm/rope k is normed+roped in place; v raw).
    xn = (x[:B] / np.sqrt((x[:B] ** 2).mean(-1, keepdims=True) + 1e-6)
          ) * w["attn_norm"]
    np.testing.assert_allclose(np.asarray(v_new)[:B], xn @ w["wv"],
                               rtol=2e-3, atol=2e-3)


def test_decode_step_bf16_workspace():
    """bf16 workspace (halves every tile DMA; fp32 compute) must track the
    fp32 result within bf16 tolerance."""
    hidden, hq, hkv, ffn, S, pos, B = 256, 2, 1, 256, 256, 100, 4
    rng = np.random.default_rng(5)
    prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                             ffn_local=ffn, num_layers=1, max_seq=S,
                             pos=pos, num_ranks=1)
    w = _rand_layer_weights(rng, hidden, hq, hkv, ffn, pos)
    kT_np = [rng.standard_normal((TILE, S)).astype(np.float32) * 0.3
             for _ in range(hkv)]
    v_np = [rng.standard_normal((S, TILE)).astype(np.float32) * 0.3
            for _ in range(hkv)]
    x = np.zeros((TILE, hidden), np.float32)
    x[:B] = rng.standard_normal((B, hidden)).astype(np.float32) * 0.3

    compiled = prog.mb.compile(dtype=jnp.bfloat16)
    feeds = {prog.x: jnp.asarray(x), prog.cos: jnp.asarray(w["cos_full"]),
             prog.sin: jnp.asarray(w["sin_full"])}
    feeds.update({k: _j(val) for k, val in
                  _feed_layer(prog, prog.layers[0], w, kT_np, v_np).items()})
    (out,) = compiled.run(feeds, outputs=[prog.x_out])
    assert out.dtype == jnp.bfloat16

    ref = _golden_layer(x[:B], w, pos, kT_np, v_np, hq, hkv)
    np.testing.assert_allclose(np.asarray(out).astype(np.float32)[:B], ref,
                               rtol=0.1, atol=0.12)


def test_decode_queue_reuse_across_positions():
    """One compiled program serves every decode position: build at
    max_seq-1, retarget with advance_queue_pos (runtime queue words), feed
    the position's rope tables — no recompile (the CUDA-graph-replay
    analog)."""
    import dataclasses

    from triton_distributed_tpu.megakernel.models import advance_queue_pos

    hidden, hq, hkv, ffn, S, B = 256, 2, 1, 256, 256, 3
    rng = np.random.default_rng(2)
    prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                             ffn_local=ffn, num_layers=1, max_seq=S,
                             pos=S - 1, num_ranks=1)
    compiled = prog.mb.compile()
    w = _rand_layer_weights(rng, hidden, hq, hkv, ffn, S - 1)
    kT_np = [rng.standard_normal((TILE, S)).astype(np.float32) * 0.3
             for _ in range(hkv)]
    v_np = [rng.standard_normal((S, TILE)).astype(np.float32) * 0.3
            for _ in range(hkv)]
    x = np.zeros((TILE, hidden), np.float32)
    x[:B] = rng.standard_normal((B, hidden)).astype(np.float32) * 0.3

    # Two retarget points (earliest + near-capacity) prove the
    # no-recompile contract; the third midpoint bought no extra coverage
    # for a full interpret execution (~18 s of tier-1 budget).
    for pos in (1, 200):
        cos_full, sin_full = rope_tables(pos, TILE, 1e6)
        step = dataclasses.replace(compiled,
                                   queue=advance_queue_pos(compiled.queue,
                                                           pos))
        feeds = {prog.x: jnp.asarray(x), prog.cos: jnp.asarray(cos_full),
                 prog.sin: jnp.asarray(sin_full)}
        feeds.update({k: _j(val) for k, val in _feed_layer(
            prog, prog.layers[0], w, kT_np, v_np).items()})
        (out,) = step.run(feeds, outputs=[prog.x_out])

        w_pos = dict(w, cos_h=cos_full[0, :TILE // 2],
                     sin_h=sin_full[0, :TILE // 2])
        ref = _golden_layer(x[:B], w_pos, pos, kT_np, v_np, hq, hkv)
        np.testing.assert_allclose(np.asarray(out)[:B], ref,
                                   rtol=2e-3, atol=2e-3)


def test_decode_step_tp8(ctx):
    """TP=8 over the CPU mesh: per-device head/ffn shards + in-kernel AR."""
    hidden, HQ, HKV, FFN, S, pos, B = 256, 8, 8, 1024, 128, 60, 2
    n = 8
    hq, hkv, ffn = HQ // n, HKV // n, FFN // n
    rng = np.random.default_rng(1)
    prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                             ffn_local=ffn, num_layers=1, max_seq=S,
                             pos=pos, num_ranks=n)
    compiled = prog.mb.compile(num_ranks=n, axis="tp")

    # Global weights; device r takes head/ffn shard r.
    W = _rand_layer_weights(rng, hidden, HQ, HKV, FFN, pos)
    kT_all = [rng.standard_normal((TILE, S)).astype(np.float32) * 0.3
              for _ in range(HKV)]
    v_all = [rng.standard_normal((S, TILE)).astype(np.float32) * 0.3
             for _ in range(HKV)]
    x = np.zeros((TILE, hidden), np.float32)
    x[:B] = rng.standard_normal((B, hidden)).astype(np.float32) * 0.3

    d = TILE
    h = prog.layers[0]

    def shard_feeds(r):
        w_r = dict(W)
        w_r["wq"] = W["wq"][:, r * hq * d:(r + 1) * hq * d]
        w_r["wk"] = W["wk"][:, r * hkv * d:(r + 1) * hkv * d]
        w_r["wv"] = W["wv"][:, r * hkv * d:(r + 1) * hkv * d]
        w_r["wo"] = W["wo"][r * hq * d:(r + 1) * hq * d]
        w_r["w_gate"] = W["w_gate"][:, r * ffn:(r + 1) * ffn]
        w_r["w_up"] = W["w_up"][:, r * ffn:(r + 1) * ffn]
        w_r["w_down"] = W["w_down"][r * ffn:(r + 1) * ffn]
        kT_r = kT_all[r * hkv:(r + 1) * hkv]
        v_r = v_all[r * hkv:(r + 1) * hkv]
        return _feed_layer(prog, h, w_r, kT_r, v_r)

    # Stack per-rank feeds into (n, ...) arrays keyed by handle.
    handles = list(shard_feeds(0).keys())
    stacked = {k: np.stack([shard_feeds(r)[k] for r in range(n)])
               for k in handles}

    def device_fn(*per_rank):
        feeds = {k: v[0] for k, v in zip(handles, per_rank)}
        feeds[prog.x] = jnp.asarray(x)
        feeds[prog.cos] = jnp.asarray(W["cos_full"])
        feeds[prog.sin] = jnp.asarray(W["sin_full"])
        (out,) = compiled.run(feeds, outputs=[prog.x_out])
        return out[None]

    fn = shard_map_on(ctx, device_fn,
                      tuple(P("tp") for _ in handles), P("tp"))
    got = np.asarray(fn(*[jnp.asarray(stacked[k]) for k in handles]))

    ref = _golden_layer(x[:B], W, pos, kT_all, v_all, HQ, HKV)
    for r in range(n):
        np.testing.assert_allclose(got[r][:B], ref, rtol=5e-3, atol=5e-3)


def test_decode_step_batch_two_tiles_matches_golden():
    """batch = 2·TILE (round-9 row-blocked emission): every TILE-chunk
    of the batch gets its own task row, outputs ride x_out_blocks, and
    the whole 256-row batch matches the eager golden."""
    hidden, hq, hkv, ffn, S, pos = 256, 2, 1, 256, 256, 100
    B = 2 * TILE
    rng = np.random.default_rng(7)
    prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                             ffn_local=ffn, num_layers=1, max_seq=S,
                             pos=pos, num_ranks=1, batch=B)
    comp = prog.mb.compile()
    w = _rand_layer_weights(rng, hidden, hq, hkv, ffn, pos)
    kT_np = [rng.standard_normal((TILE, S)).astype(np.float32) * 0.3]
    v_np = [rng.standard_normal((S, TILE)).astype(np.float32) * 0.3]
    x = rng.standard_normal((B, hidden)).astype(np.float32) * 0.3
    feeds = {prog.x: jnp.asarray(x), prog.cos: jnp.asarray(w["cos_full"]),
             prog.sin: jnp.asarray(w["sin_full"])}
    feeds.update({k: _j(v) for k, v in _feed_layer(
        prog, prog.layers[0], w, kT_np, v_np).items()})
    assert prog.blocks == 2 and len(prog.x_out_blocks) == 2
    outs = comp.run(feeds, outputs=prog.x_out_blocks)
    got = np.concatenate([np.asarray(o) for o in outs], axis=0)
    ref = _golden_layer(x, w, pos, kT_np, v_np, hq, hkv)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_decode_step_head_dim_64_matches_golden():
    """head_dim 64 (round-9 padded-head layout, the Qwen3-0.6B/1.7B
    presets): each head lives in the low 64 lanes of its tile, the
    norm/rope sub-tile math spans head_dim, and the result matches an
    eager d=64 golden."""
    from triton_distributed_tpu.megakernel.models import pad_head_vec

    hd = 64
    hidden, hq, hkv, ffn, S, pos, B = 256, 2, 1, 256, 256, 100, 3
    rng = np.random.default_rng(3)
    prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                             ffn_local=ffn, num_layers=1, max_seq=S,
                             pos=pos, num_ranks=1, head_dim=hd)
    comp = prog.mb.compile(head_dim=hd)
    h = prog.layers[0]
    w = {k: rng.standard_normal(s).astype(np.float32) * 0.05 for k, s in [
        ("wq", (hidden, hq * hd)), ("wk", (hidden, hkv * hd)),
        ("wv", (hidden, hkv * hd)), ("wo", (hq * hd, hidden)),
        ("w_gate", (hidden, ffn)), ("w_up", (hidden, ffn)),
        ("w_down", (ffn, hidden))]}
    anorm = rng.standard_normal(hidden).astype(np.float32) * 0.1 + 1
    mnorm = rng.standard_normal(hidden).astype(np.float32) * 0.1 + 1
    qn = rng.standard_normal(hd).astype(np.float32) * 0.1 + 1
    kn = rng.standard_normal(hd).astype(np.float32) * 0.1 + 1
    # Cache in the PADDED tile layout: real rows/cols [0:hd], pad zero.
    kc = rng.standard_normal((hd, S)).astype(np.float32) * 0.3
    vc = rng.standard_normal((S, hd)).astype(np.float32) * 0.3
    kT_pad = np.zeros((TILE, S), np.float32)
    kT_pad[:hd] = kc
    v_pad = np.zeros((S, TILE), np.float32)
    v_pad[:, :hd] = vc
    cos, sin = rope_tables(pos, hd, 1e6)
    x = np.zeros((TILE, hidden), np.float32)
    x[:B] = rng.standard_normal((B, hidden)).astype(np.float32) * 0.3
    feeds = {prog.x: jnp.asarray(x), prog.cos: jnp.asarray(cos),
             prog.sin: jnp.asarray(sin),
             h.attn_norm: jnp.asarray(broadcast_rows(anorm)),
             h.mlp_norm: jnp.asarray(broadcast_rows(mnorm)),
             h.q_norm: jnp.asarray(broadcast_rows(pad_head_vec(qn, hd))),
             h.k_norm: jnp.asarray(broadcast_rows(pad_head_vec(kn, hd))),
             h.kT[0]: jnp.asarray(kT_pad), h.v[0]: jnp.asarray(v_pad)}
    feed_layer_weights(feeds, h, head_dim=hd,
                       **{k: jnp.asarray(v) for k, v in w.items()})
    feeds = {k: (tuple(jnp.asarray(e) for e in v) if isinstance(v, tuple)
                 else jnp.asarray(v)) for k, v in feeds.items()}
    (out,) = comp.run(feeds, outputs=[prog.x_out])

    def rms(a, g, eps=1e-6):
        return (a / np.sqrt((a ** 2).mean(-1, keepdims=True) + eps)) * g

    def rope(a, ch, sh):
        a1, a2 = a[:, :hd // 2], a[:, hd // 2:]
        return np.concatenate([a1 * ch - a2 * sh, a2 * ch + a1 * sh], 1)

    ch, sh = cos[0, :hd // 2], sin[0, :hd // 2]
    xb = x[:B]
    xn = rms(xb, anorm)
    q = xn @ w["wq"]
    k_new = xn @ w["wk"]
    v_new = xn @ w["wv"]
    groups = hq // hkv
    attn = np.zeros_like(q)
    for j in range(hq):
        kv = j // groups
        qj = rope(rms(q[:, j * hd:(j + 1) * hd], qn), ch, sh)
        kj = rope(rms(k_new[:, kv * hd:(kv + 1) * hd], kn), ch, sh)
        vj = v_new[:, kv * hd:(kv + 1) * hd]
        s_cache = (qj @ kc[:, :pos]) * hd ** -0.5
        s_cur = (qj * kj).sum(-1, keepdims=True) * hd ** -0.5
        s = np.concatenate([s_cache, s_cur], axis=1)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        attn[:, j * hd:(j + 1) * hd] = (p[:, :pos] @ vc[:pos]
                                        + p[:, pos:] * vj)
    x1 = xb + attn @ w["wo"]
    x1n = rms(x1, mnorm)
    g = x1n @ w["w_gate"]
    act = g / (1 + np.exp(-g)) * (x1n @ w["w_up"])
    ref = x1 + act @ w["w_down"]
    np.testing.assert_allclose(np.asarray(out)[:B], ref,
                               rtol=3e-3, atol=3e-3)


def test_paged_decode_step_matches_linear():
    """build_decode_step(paged=True): attention walks page-table DATA rows
    over the kT/v pools; with identity tables it equals the linear decode
    step exactly (the reference megakernel's PagedKVCache assembly)."""
    hidden, hq, hkv, ffn, S, pos = 256, 2, 1, 256, 256, 100
    rng = np.random.default_rng(5)
    feed_vals = {}

    def build(paged):
        prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                                 ffn_local=ffn, num_layers=1, max_seq=S,
                                 pos=pos, num_ranks=1, paged=paged)
        comp = prog.mb.compile()
        h = prog.layers[0]
        cos, sin = rope_tables(pos, TILE, 1e6)
        if not feed_vals:   # generate once, reuse for both variants
            feed_vals["x"] = rng.standard_normal((TILE, hidden)) * 0.3
            feed_vals["w"] = {
                n: rng.standard_normal(s) * 0.05 for n, s in [
                    ("wq", (hidden, hq * TILE)), ("wk", (hidden, hkv * TILE)),
                    ("wv", (hidden, hkv * TILE)), ("wo", (hq * TILE, hidden)),
                    ("w_gate", (hidden, ffn)), ("w_up", (hidden, ffn)),
                    ("w_down", (ffn, hidden))]
            }
            feed_vals["kT"] = rng.standard_normal((TILE, S)) * 0.3
            feed_vals["v"] = rng.standard_normal((S, TILE)) * 0.3
        ones_h = broadcast_rows(np.ones(hidden, np.float32))
        ones_d = broadcast_rows(np.ones(TILE, np.float32))
        feeds = {prog.x: feed_vals["x"], prog.cos: cos, prog.sin: sin,
                 h.attn_norm: ones_h, h.mlp_norm: ones_h,
                 h.q_norm: ones_d, h.k_norm: ones_d,
                 h.kT[0]: feed_vals["kT"], h.v[0]: feed_vals["v"]}
        feed_layer_weights(feeds, h, **{
            n_: np.asarray(v_, np.float32)
            for n_, v_ in feed_vals["w"].items()})
        feeds = {k_: _j(v_) if isinstance(v_, tuple)
                 else jnp.asarray(np.asarray(v_, np.float32))
                 for k_, v_ in feeds.items()}
        (out,) = comp.run(feeds, outputs=[prog.x_out])
        return np.asarray(out)

    linear = build(paged=False)
    paged = build(paged=True)
    np.testing.assert_allclose(paged, linear, rtol=1e-5, atol=1e-5)


def _golden_moe_ffn(x1n, router, wg, wu, wd, topk):
    """Eager MoE FFN golden: fp32 router → top-k (leftmost tie-break) →
    softmax over selected → expert SwiGLU (ops/moe.route_and_sort
    semantics)."""
    B = x1n.shape[0]
    E = router.shape[1]
    logits = x1n @ router
    out = np.zeros_like(x1n)
    for t in range(B):
        order = np.argsort(-logits[t], kind="stable")[:topk]
        sel = logits[t, order]
        w = np.exp(sel - sel.max())
        w /= w.sum()
        for j, e in enumerate(order):
            g = x1n[t] @ wg[e]
            act = g / (1 + np.exp(-g)) * (x1n[t] @ wu[e])
            out[t] += w[j] * (act @ wd[e])
    return out


def test_decode_step_moe_single_device():
    """Qwen3-MoE decode layer as one megakernel: router GEMM → MOE_TOPK →
    expert-skipping MOE_FFN, vs the eager golden (the layer-path routing
    semantics of ops/moe.route_and_sort)."""
    hidden, hq, hkv, S, pos, B = 256, 2, 1, 256, 100, 4
    E, topk, ffn = 8, 2, 128
    rng = np.random.default_rng(3)
    prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                             ffn_local=ffn, num_layers=1, max_seq=S,
                             pos=pos, num_ranks=1, moe_experts=E,
                             moe_topk=topk, batch=B)
    w = _rand_layer_weights(rng, hidden, hq, hkv, ffn, pos)
    router = rng.standard_normal((hidden, E)).astype(np.float32) * 0.2
    wg = rng.standard_normal((E, hidden, ffn)).astype(np.float32) * 0.05
    wu = rng.standard_normal((E, hidden, ffn)).astype(np.float32) * 0.05
    wd = rng.standard_normal((E, ffn, hidden)).astype(np.float32) * 0.05
    kT_np = [rng.standard_normal((TILE, S)).astype(np.float32) * 0.3
             for _ in range(hkv)]
    v_np = [rng.standard_normal((S, TILE)).astype(np.float32) * 0.3
            for _ in range(hkv)]
    x = np.zeros((TILE, hidden), np.float32)
    x[:B] = rng.standard_normal((B, hidden)).astype(np.float32) * 0.3

    compiled = prog.mb.compile()
    h = prog.layers[0]
    feeds = {prog.x: jnp.asarray(x), prog.cos: jnp.asarray(w["cos_full"]),
             prog.sin: jnp.asarray(w["sin_full"])}
    base = _feed_layer(prog, h, w, kT_np, v_np)
    # _feed_layer fed the dense-alias fields; replace with MoE feeds.
    for k in (h.w_gate, h.w_up, h.w_down):
        base.pop(k, None)
    base[h.moe_router] = np.pad(router, ((0, 0), (0, TILE - E)))
    base[h.moe_w_gate] = wg.reshape(E * hidden, ffn)
    base[h.moe_w_up] = wu.reshape(E * hidden, ffn)
    base[h.moe_w_down] = wd.reshape(E * ffn, hidden)
    feeds.update({k: _j(val) for k, val in base.items()})
    out, = compiled.run(feeds, outputs=[prog.x_out])

    # Golden: attention part from _golden_layer with zeroed FFN, plus the
    # MoE FFN applied to its x1.
    d = TILE
    eps = 1e-6

    def rms(a, g):
        return (a / np.sqrt((a ** 2).mean(-1, keepdims=True) + eps)) * g

    wz = dict(w)
    wz["w_gate"] = np.zeros((hidden, ffn), np.float32)
    wz["w_up"] = np.zeros((hidden, ffn), np.float32)
    wz["w_down"] = np.zeros((ffn, hidden), np.float32)
    x1 = _golden_layer(x[:B], wz, pos, kT_np, v_np, hq, hkv)  # = x1 (FFN=0)
    x1n = rms(x1, w["mlp_norm"])
    ref = x1 + _golden_moe_ffn(x1n, router, wg, wu, wd, topk)
    np.testing.assert_allclose(np.asarray(out)[:B], ref, rtol=2e-3,
                               atol=2e-3)


def test_decode_step_moe_tp2_virtual_mesh():
    """TP-sharded MoE decode (experts ffn-sharded, AR combine) on a 2-dev
    virtual mesh: token-identical to the replicated eager golden."""
    hidden, hq, hkv, S, pos, B = 256, 2, 1, 256, 60, 2
    E, topk, ffn, n = 8, 2, 256, 2
    ffn_local = ffn // n
    rng = np.random.default_rng(4)
    prog = build_decode_step(hidden=hidden, hq_local=hq // n,
                             hkv_local=hkv, ffn_local=ffn_local,
                             num_layers=1, max_seq=S, pos=pos,
                             num_ranks=n, moe_experts=E, moe_topk=topk,
                             batch=B)
    compiled = prog.mb.compile(num_ranks=n, axis="tp")
    h = prog.layers[0]

    w = _rand_layer_weights(rng, hidden, hq, hkv, ffn, pos)
    router = rng.standard_normal((hidden, E)).astype(np.float32) * 0.2
    wg = rng.standard_normal((E, hidden, ffn)).astype(np.float32) * 0.05
    wu = rng.standard_normal((E, hidden, ffn)).astype(np.float32) * 0.05
    wd = rng.standard_normal((E, ffn, hidden)).astype(np.float32) * 0.05
    kT_np = [rng.standard_normal((TILE, S)).astype(np.float32) * 0.3
             for _ in range(hkv)]
    v_np = [rng.standard_normal((S, TILE)).astype(np.float32) * 0.3
            for _ in range(hkv)]
    x = np.zeros((TILE, hidden), np.float32)
    x[:B] = rng.standard_normal((B, hidden)).astype(np.float32) * 0.3

    def run_rank(r):
        """Device-local feeds for rank r (q heads + expert ffn sharded)."""
        hq_l = hq // n
        wr = dict(w)
        wr["wq"] = w["wq"][:, r * hq_l * TILE:(r + 1) * hq_l * TILE]
        wr["wo"] = w["wo"][r * hq_l * TILE:(r + 1) * hq_l * TILE]
        feeds = {prog.x: x, prog.cos: w["cos_full"],
                 prog.sin: w["sin_full"]}
        base = _feed_layer(prog, h, wr, kT_np, v_np)
        for kk in (h.w_gate, h.w_up, h.w_down):
            base.pop(kk, None)
        f0, f1 = r * ffn_local, (r + 1) * ffn_local
        base[h.moe_router] = np.pad(router, ((0, 0), (0, TILE - E)))
        base[h.moe_w_gate] = wg[:, :, f0:f1].reshape(E * hidden, ffn_local)
        base[h.moe_w_up] = wu[:, :, f0:f1].reshape(E * hidden, ffn_local)
        base[h.moe_w_down] = wd[:, f0:f1].reshape(E * ffn_local, hidden)
        feeds.update(base)
        return feeds

    feeds_by_rank = [run_rank(r) for r in range(n)]
    # Stack per-rank feeds for shard_map over the leading axis.
    keys = list(feeds_by_rank[0].keys())
    stacked = [jnp.asarray(np.stack([np.asarray(fr[k], np.float32)
                                     for fr in feeds_by_rank]))
               for k in keys]

    import triton_distributed_tpu as tdt

    ctx = tdt.initialize_distributed(
        devices=jax.devices()[:n], axis_names=("tp",))

    def local(*vals):
        main, _w8, wm = compiled.split_feeds(
            {k: v[0] for k, v in zip(keys, vals)})
        ws = compiled.make_workspace(main)
        wsm = compiled.make_workspace_mat(wm) if wm else None
        ws = compiled.step(ws, wsm=wsm)
        return compiled.gather_output(ws, prog.x_out)[None]

    out = shard_map_on(ctx, local, tuple(P("tp") for _ in keys),
                       P("tp"))(*stacked)
    out = np.asarray(out)

    # Golden (replicated math over full heads + full ffn).
    d = TILE
    eps = 1e-6

    def rms(a, g):
        return (a / np.sqrt((a ** 2).mean(-1, keepdims=True) + eps)) * g

    wz = dict(w)
    wz["w_gate"] = np.zeros((hidden, ffn), np.float32)
    wz["w_up"] = np.zeros((hidden, ffn), np.float32)
    wz["w_down"] = np.zeros((ffn, hidden), np.float32)
    x1 = _golden_layer(x[:B], wz, pos, kT_np, v_np, hq, hkv)
    x1n = rms(x1, w["mlp_norm"])
    ref = x1 + _golden_moe_ffn(x1n, router, wg, wu, wd, topk)
    for r in range(n):
        np.testing.assert_allclose(out[r][:B], ref, rtol=2e-3, atol=2e-3)


def _golden_stack(x, ws, pos, kTs, vs, hq, hkv, fnorm=None):
    """Chain _golden_layer over per-layer weight dicts; optional final
    RMSNorm (the in-kernel final_norm=True contract)."""
    cur = x
    for w, kT, v in zip(ws, kTs, vs):
        cur = _golden_layer(cur, w, pos, kT, v, hq, hkv)
    if fnorm is not None:
        cur = (cur / np.sqrt((cur ** 2).mean(-1, keepdims=True) + 1e-6)
               ) * fnorm
    return cur


def _multilayer_setup(rng, hidden, hq, hkv, ffn, S, pos, B, L):
    ws = [_rand_layer_weights(rng, hidden, hq, hkv, ffn, pos)
          for _ in range(L)]
    kTs = [[rng.standard_normal((TILE, S)).astype(np.float32) * 0.3
            for _ in range(hkv)] for _ in range(L)]
    vs = [[rng.standard_normal((S, TILE)).astype(np.float32) * 0.3
           for _ in range(hkv)] for _ in range(L)]
    x = np.zeros((TILE, hidden), np.float32)
    x[:B] = rng.standard_normal((B, hidden)).astype(np.float32) * 0.3
    return ws, kTs, vs, x


def test_decode_step_multilayer_cross_layer_fusion():
    """2-layer dense decode at n=1 with final_norm=True: the round-6
    fused assembly (whole-row NORM_ROPE_QKV, GEMM_MAT epilogue-3 folding
    every residual add + the NEXT consumer's norm into the producing GEMM
    — across the layer seam AND into the model's final norm) must be
    parity with the eager chained golden. One program covers both fusion
    boundaries; the unfused-tail (final_norm=False) form is exercised by
    test_decode_step_single_device and the MoE cases."""
    hidden, hq, hkv, ffn, S, pos, B, L = 256, 2, 1, 256, 256, 100, 4, 2
    rng = np.random.default_rng(11)
    ws, kTs, vs, x = _multilayer_setup(rng, hidden, hq, hkv, ffn, S, pos,
                                       B, L)
    fnorm = rng.standard_normal(hidden).astype(np.float32) * 0.1 + 1
    prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                             ffn_local=ffn, num_layers=L, max_seq=S,
                             pos=pos, num_ranks=1, final_norm=True)
    assert prog.fnorm is not None
    compiled = prog.mb.compile()
    # The fused assembly must actually be fused: epilogue-3 GEMM_MAT
    # replaces the standalone norms, so only layer 0's rms_norm survives
    # and nothing dispatches per-head NORM_ROPE or a standalone ADD.
    from triton_distributed_tpu.megakernel.tasks import TaskType

    q = np.asarray(compiled.queue)[:compiled.num_exec, 0]
    assert (q == int(TaskType.RMS_NORM)).sum() == 1
    assert (q == int(TaskType.NORM_ROPE)).sum() == 0
    assert (q == int(TaskType.NORM_ROPE_QKV)).sum() == L
    assert (q == int(TaskType.ADD)).sum() == 0

    feeds = {prog.x: jnp.asarray(x),
             prog.cos: jnp.asarray(ws[0]["cos_full"]),
             prog.sin: jnp.asarray(ws[0]["sin_full"]),
             prog.fnorm: jnp.asarray(broadcast_rows(fnorm))}
    for li, h in enumerate(prog.layers):
        feeds.update({k: _j(val) for k, val in
                      _feed_layer(prog, h, ws[li], kTs[li],
                                  vs[li]).items()})
    (out,) = compiled.run(feeds, outputs=[prog.x_out])
    ref = _golden_stack(x[:B], ws, pos, kTs, vs, hq, hkv, fnorm=fnorm)
    np.testing.assert_allclose(np.asarray(out)[:B], ref, rtol=5e-3,
                               atol=5e-3)


def test_decode_step_multilayer_moe():
    """2-layer MoE decode at n=1: the cross-layer ADD_NORM boundary (the
    MoE tail cannot fuse into a GEMM epilogue) must be parity with the
    chained eager golden."""
    hidden, hq, hkv, S, pos, B, L = 256, 2, 1, 128, 60, 4, 2
    E, topk, ffn = 4, 2, 128
    rng = np.random.default_rng(13)
    ws, kTs, vs, x = _multilayer_setup(rng, hidden, hq, hkv, ffn, S, pos,
                                       B, L)
    routers = [rng.standard_normal((hidden, E)).astype(np.float32) * 0.2
               for _ in range(L)]
    wg = [rng.standard_normal((E, hidden, ffn)).astype(np.float32) * 0.05
          for _ in range(L)]
    wu = [rng.standard_normal((E, hidden, ffn)).astype(np.float32) * 0.05
          for _ in range(L)]
    wd = [rng.standard_normal((E, ffn, hidden)).astype(np.float32) * 0.05
          for _ in range(L)]
    prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                             ffn_local=ffn, num_layers=L, max_seq=S,
                             pos=pos, num_ranks=1, moe_experts=E,
                             moe_topk=topk, batch=B)
    from triton_distributed_tpu.megakernel.tasks import TaskType

    compiled = prog.mb.compile()
    q = np.asarray(compiled.queue)[:compiled.num_exec, 0]
    # The layer-seam boundary is the fused ADD_NORM (layers 0..L-2); the
    # last layer ends with a plain ADD (no consumer norm).
    assert (q == int(TaskType.ADD_NORM)).sum() == L - 1
    assert (q == int(TaskType.ADD)).sum() == 1

    feeds = {prog.x: jnp.asarray(x),
             prog.cos: jnp.asarray(ws[0]["cos_full"]),
             prog.sin: jnp.asarray(ws[0]["sin_full"])}
    for li, h in enumerate(prog.layers):
        base = _feed_layer(prog, h, ws[li], kTs[li], vs[li])
        for k in (h.w_gate, h.w_up, h.w_down):
            base.pop(k, None)
        base[h.moe_router] = np.pad(routers[li], ((0, 0), (0, TILE - E)))
        base[h.moe_w_gate] = wg[li].reshape(E * hidden, ffn)
        base[h.moe_w_up] = wu[li].reshape(E * hidden, ffn)
        base[h.moe_w_down] = wd[li].reshape(E * ffn, hidden)
        feeds.update({k: _j(val) for k, val in base.items()})
    (out,) = compiled.run(feeds, outputs=[prog.x_out])

    eps = 1e-6

    def rms(a, g):
        return (a / np.sqrt((a ** 2).mean(-1, keepdims=True) + eps)) * g

    cur = x[:B]
    for li in range(L):
        wz = dict(ws[li])
        wz["w_gate"] = np.zeros((hidden, ffn), np.float32)
        wz["w_up"] = np.zeros((hidden, ffn), np.float32)
        wz["w_down"] = np.zeros((ffn, hidden), np.float32)
        x1 = _golden_layer(cur, wz, pos, kTs[li], vs[li], hq, hkv)
        x1n = rms(x1, ws[li]["mlp_norm"])
        cur = x1 + _golden_moe_ffn(x1n, routers[li], wg[li], wu[li],
                                   wd[li], topk)
    np.testing.assert_allclose(np.asarray(out)[:B], cur, rtol=5e-3,
                               atol=5e-3)


def test_add_norm_task_matches_unfused_pair():
    """ADD_NORM must be BIT-identical to the add + rms_norm task pair
    (the norm reads the stored wdt-rounded x2 — the fusion contract).
    Both chains run in ONE program/launch so the comparison costs a
    single interpret execution."""
    from triton_distributed_tpu.megakernel.builder import MegaKernelBuilder

    rng = np.random.default_rng(14)
    cols = 512
    a_v = rng.standard_normal((TILE, cols)).astype(np.float32) * 0.3
    b_v = rng.standard_normal((TILE, cols)).astype(np.float32) * 0.3
    w_v = rng.standard_normal((cols,)).astype(np.float32) * 0.1 + 1

    mb = MegaKernelBuilder()
    a = mb.tensor(TILE, cols)
    b = mb.tensor(TILE, cols)
    w = mb.tensor(TILE, cols)
    fx2 = mb.tensor(TILE, cols)
    fxn = mb.tensor(TILE, cols)
    ux2 = mb.tensor(TILE, cols)
    uxn = mb.tensor(TILE, cols)
    mb.add_norm(fx2, a, b, w, fxn)          # fused
    mb.add(ux2, a, b)                       # unfused pair
    mb.rms_norm(uxn, ux2, w)
    comp = mb.compile()
    outs = comp.run({a: jnp.asarray(a_v), b: jnp.asarray(b_v),
                     w: jnp.asarray(broadcast_rows(w_v))},
                    outputs=[fx2, fxn, ux2, uxn])
    f2, fn_, u2, un_ = (np.asarray(o) for o in outs)
    np.testing.assert_array_equal(f2, u2)
    np.testing.assert_array_equal(fn_, un_)


def test_force_ar_program_structure():
    """force_ar_tasks=True at n=1: the in-kernel AR sites are emitted (2
    per layer — one ALLREDUCE_ROW per reduction site since the slab
    rework) and the program compiles with force_ar (the cross-device
    rung's configuration; executing the loopback remote DMA needs real
    hardware — scripts/check_on_chip.py gates that)."""
    from triton_distributed_tpu.megakernel.tasks import TaskType

    L = 2
    prog = build_decode_step(hidden=256, hq_local=2, hkv_local=1,
                             ffn_local=256, num_layers=L, max_seq=256,
                             pos=100, num_ranks=1, force_ar_tasks=True)
    comp = prog.mb.compile(force_ar=True)
    assert comp.force_ar
    q = np.asarray(comp.queue)[:comp.num_exec, 0]
    assert (q == int(TaskType.ALLREDUCE_ROW)).sum() == 2 * L
    # The AR path replaces the GEMM-epilogue fusion with ADD_NORM at both
    # sites of every layer except the last layer's tail (plain ADD).
    assert (q == int(TaskType.ADD_NORM)).sum() == 2 * L - 1
    assert (q == int(TaskType.ADD)).sum() == 1


def test_build_decode_step_named_errors():
    """Every TILE/geometry constraint raises at build time naming the
    offending dimension AND the config field (VERDICT r5 weak #7) — one
    case per constraint."""
    import pytest

    ok = dict(hidden=256, hq_local=2, hkv_local=1, ffn_local=256,
              num_layers=1, max_seq=256, pos=0)

    def build(**kw):
        return build_decode_step(**{**ok, **kw})

    # Round 9 lifted the two Qwen3-8B-only dims: head_dim 64 and
    # batch > TILE now BUILD (parity tests cover their execution);
    # anything else stays a named error.
    assert build(head_dim=64).layers
    assert build(batch=200).blocks == 2
    with pytest.raises(ValueError, match=r"head_dim = 96.*head_dim"):
        build(head_dim=96)
    with pytest.raises(ValueError, match=r"hidden = 200.*hidden_size"):
        build(hidden=200)
    with pytest.raises(ValueError,
                       match=r"ffn_local = 100.*intermediate_size"):
        build(ffn_local=100)
    with pytest.raises(ValueError, match=r"max_seq = 100.*max_seq"):
        build(max_seq=100)
    with pytest.raises(ValueError, match=r"batch = 200.*fp8"):
        build(batch=200, fp8_weights=True)
    with pytest.raises(ValueError, match=r"batch = 200.*inkernel_append"):
        build(batch=200, inkernel_append=True)
    with pytest.raises(ValueError, match=r"batch = 0"):
        build(batch=0)
    with pytest.raises(ValueError, match=r"num_layers = 0.*num_layers"):
        build(num_layers=0)
    with pytest.raises(ValueError, match=r"hkv_local = 0.*num_kv_heads"):
        build(hkv_local=0)
    with pytest.raises(ValueError,
                       match=r"hq_local = 3.*hkv_local = 2"):
        build(hq_local=3, hkv_local=2)
    with pytest.raises(ValueError, match=r"moe_topk.*num_experts"):
        build(moe_experts=4, moe_topk=5)
    with pytest.raises(ValueError, match=r"pos 256 outside"):
        build(pos=256)


def test_full_model_profile_attribution():
    """The full-model queue's per-class lanes are fully attributed: every
    task in the build-time plan (records_from_queue — the queue IS the
    dispatch plan) lands in a named class and the accounting covers the
    whole queue (the unattributed-growth gate). The stamped-profile-vs-
    plan parity of a REAL step is exercised by the CI obs-smoke step
    (`scripts/mk_profile.py --full-model` asserts it) — repeating the
    interpret execution here would double-pay its cost."""
    from triton_distributed_tpu.obs.kernel_profile import (
        KernelProfile, attach_durations, records_from_queue,
    )

    prog = build_decode_step(hidden=256, hq_local=2, hkv_local=1,
                             ffn_local=256, num_layers=2, max_seq=256,
                             pos=100, num_ranks=1, final_norm=True)
    compiled = prog.mb.compile()
    plan = records_from_queue(compiled.queue, compiled.num_exec)
    assert all(r.task_class != "other" for r in plan), \
        "unclassified task type in the decode queue"

    attach_durations(plan)
    kp = KernelProfile(records=plan, measured_step_s=None)
    acct = kp.accounting(host_s=1e-4)
    assert acct["unclassified"] == 0
    assert set(acct["classes"]) == {"gemm", "norm", "attention"}
    # Per-class lanes must cover every dispatched task.
    assert sum(d["tasks"] for d in acct["classes"].values()) \
        == compiled.num_exec
    # Every lane carries a duration (est: or measured) — an undurationed
    # record would render a zero-width slice and silently hide work.
    assert all(r.duration_s and r.duration_kind != "none" for r in plan)


def test_feed_layer_weights_rejects_lone_gate_or_up():
    """Exactly one of w_gate/w_up must fail at the call site, not later
    as an opaque jnp.asarray(None) crash inside scatter_mat."""
    import pytest

    prog = build_decode_step(hidden=256, hq_local=2, hkv_local=1,
                             ffn_local=256, num_layers=1, max_seq=128,
                             pos=0)
    h = prog.layers[0]
    d = 128
    wq = np.zeros((256, 2 * d), np.float32)
    wkv = np.zeros((256, d), np.float32)
    wo = np.zeros((2 * d, 256), np.float32)
    with pytest.raises(ValueError, match="BOTH w_gate and w_up"):
        feed_layer_weights({}, h, wq=wq, wk=wkv, wv=wkv, wo=wo,
                           w_gate=np.zeros((256, 256), np.float32),
                           w_up=None,
                           w_down=np.zeros((256, 256), np.float32))
    with pytest.raises(ValueError, match="BOTH w_gate and w_up"):
        feed_layer_weights({}, h, wq=wq, wk=wkv, wv=wkv, wo=wo,
                           w_gate=None,
                           w_up=np.zeros((256, 256), np.float32))
