"""Speculative multi-token decode lane (ISSUE 14, docs/serving.md
"Speculative decode").

The load-bearing contract: greedy draft-and-verify serving
(``spec_k > 0``) must be TOKEN-IDENTICAL to one-token decode on both
the xla and megakernel backends — including preempt/resume — while
rejected drafts never leave KV bytes resident (pool occupancy returns
to the one-token baseline after every iteration's rollback) and a
transient fault inside a verify step falls the lane back to one-token
decode instead of dying.
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.models.config import ModelConfig, tiny_config
from triton_distributed_tpu.models.dense import (
    dense_decode_step_paged, dense_verify_step_paged, init_dense_llm,
)
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.kv_cache import (
    PageAllocator, init_paged_model_cache,
)
from triton_distributed_tpu.models.sampling import accept_longest_prefix
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving.loop import ServingEngine
from triton_distributed_tpu.serving.spec import NGramProposer, SpecConfigError


@pytest.fixture(scope="module")
def ctx1():
    return initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])


@pytest.fixture(scope="module")
def tiny(ctx1):
    cfg = tiny_config()
    params = init_dense_llm(jax.random.key(0), cfg)
    return cfg, params


# ---------------------------------------------------------------------------
# accept_longest_prefix — the one rule both backends share.
# ---------------------------------------------------------------------------

def test_accept_empty_draft_takes_base_token():
    assert accept_longest_prefix([], [7]).tolist() == [7]


def test_accept_full_window():
    assert accept_longest_prefix([3, 4], [3, 4, 9]).tolist() == [3, 4, 9]


def test_accept_first_token_reject():
    assert accept_longest_prefix([5, 4], [3, 4, 9]).tolist() == [3]


def test_accept_partial_prefix():
    assert accept_longest_prefix([3, 6, 1], [3, 4, 9, 2]).tolist() == [3, 4]


def test_accept_dtype_and_size_contract():
    out = accept_longest_prefix(np.array([3], np.int64),
                                np.array([3, 9], np.int64))
    assert out.dtype == np.int32
    with pytest.raises(ValueError, match="k\\+1 positions"):
        accept_longest_prefix([1, 2], [1, 2])


# ---------------------------------------------------------------------------
# NGramProposer — deterministic self-drafting.
# ---------------------------------------------------------------------------

def test_proposer_copies_most_recent_continuation():
    p = NGramProposer(3, ngram=2)
    # ... 5 6 A B C ... 5 6 -> proposes A B C (the continuation of the
    # most recent EARLIER occurrence of the trailing bigram).
    hist = [1, 5, 6, 7, 8, 9, 2, 5, 6]
    assert p.propose(hist) == [7, 8, 9]


def test_proposer_recency_wins():
    p = NGramProposer(1, ngram=1)
    hist = [4, 10, 3, 4, 20, 4]
    assert p.propose(hist) == [20]       # the later occurrence's successor


def test_proposer_no_match_is_empty_and_deterministic():
    p = NGramProposer(3, ngram=3, min_ngram=3)
    assert p.propose([1, 2, 3, 4]) == []
    hist = [1, 5, 6, 7, 2, 5, 6]
    assert p.propose(hist) == p.propose(hist)


def test_proposer_cap_and_validation():
    p = NGramProposer(4, ngram=1)
    assert p.propose([9, 1, 2, 3, 4, 9], max_tokens=2) == [1, 2]
    assert p.propose([9, 1, 2, 3, 4, 9], max_tokens=0) == []
    with pytest.raises(SpecConfigError, match="spec_k=0 disables"):
        NGramProposer(0)
    with pytest.raises(SpecConfigError, match="min_ngram"):
        NGramProposer(2, ngram=1, min_ngram=3)


# ---------------------------------------------------------------------------
# PageAllocator.free_tail — the rollback primitive.
# ---------------------------------------------------------------------------

def test_free_tail_releases_allocation_order_tail():
    a = PageAllocator(8, 6)
    a.alloc_pages("r", 5)
    held = a.pages("r")
    assert a.free_tail("r", 3) == 2
    assert a.pages("r") == held[:3]
    assert a.free_count == 5
    assert a.free_tail("r", 3) == 0          # idempotent
    assert a.free_tail("ghost", 0) == 0      # unknown owner is a no-op
    with pytest.raises(ValueError, match="non-negative"):
        a.free_tail("r", -1)


def test_paged_append_window_drops_past_capacity_without_aliasing():
    """Padding rows past capacity must DROP, not clamp onto the last
    in-capacity position: a clamped duplicate index could overwrite the
    final real candidate's just-appended k/v with the stale pre-step
    value (scatter order over duplicate indices is undefined)."""
    from triton_distributed_tpu.ops.paged_attention import (
        init_paged_kv_cache, paged_append, paged_append_window,
    )

    cache = init_paged_kv_cache(1, num_pages=2, page_size=4,
                                num_kv_heads=1, head_dim=8, max_pages=2)
    cache = cache._replace(kv_lens=jnp.asarray([6], jnp.int32))
    k = jax.random.normal(jax.random.key(3), (1, 3, 1, 8))
    v = jax.random.normal(jax.random.key(4), (1, 3, 1, 8))
    # Window of 3 at base 6 over capacity 8: positions 6, 7 real, 8 OOB.
    out = paged_append_window(cache, k, v)
    assert int(out.kv_lens[0]) == 8
    # Sequential golden: two in-capacity appends, third dropped.
    seq = cache
    for i in range(3):
        seq = paged_append(seq, k[:, i], v[:, i])
    np.testing.assert_array_equal(np.asarray(out.k_pool),
                                  np.asarray(seq.k_pool))
    np.testing.assert_array_equal(np.asarray(out.v_pool),
                                  np.asarray(seq.v_pool))


# ---------------------------------------------------------------------------
# The dense verify step — bit-parity with sequential one-token decode.
# ---------------------------------------------------------------------------

def test_verify_step_matches_sequential_paged_decode(tiny):
    cfg, params = tiny
    B, W, page, mp = 2, 3, 4, 8
    cache = init_paged_model_cache(cfg, B, page_size=page, max_pages=mp)
    k1, k2 = jax.random.split(jax.random.key(1))
    cache = cache._replace(
        k_pools=jax.random.normal(k1, cache.k_pools.shape,
                                  cache.k_pools.dtype),
        v_pools=jax.random.normal(k2, cache.v_pools.shape,
                                  cache.v_pools.dtype),
        kv_lens=jnp.asarray([5, 9], jnp.int32))   # heterogeneous lengths
    toks = np.array([[3, 11, 7], [20, 5, 5]], np.int32)

    c_seq = cache
    seq_logits = []
    for i in range(W):
        lg, c_seq = dense_decode_step_paged(
            params, cfg, jnp.asarray(toks[:, i]), c_seq, num_ranks=1,
            mode="ar")
        seq_logits.append(np.asarray(lg))
    ver, c_ver = dense_verify_step_paged(params, cfg, jnp.asarray(toks),
                                         cache, num_ranks=1, mode="ar")
    ver = np.asarray(ver)
    for i in range(W):
        np.testing.assert_allclose(ver[:, i], seq_logits[i],
                                   rtol=2e-6, atol=2e-6)
        assert (ver[:, i].argmax(-1) == seq_logits[i].argmax(-1)).all()
    # The appended pool state is byte-identical: the serving rollback's
    # append-then-truncate depends on the stored values matching W
    # sequential appends exactly.
    np.testing.assert_array_equal(np.asarray(c_ver.k_pools),
                                  np.asarray(c_seq.k_pools))
    np.testing.assert_array_equal(np.asarray(c_ver.v_pools),
                                  np.asarray(c_seq.v_pools))
    np.testing.assert_array_equal(np.asarray(c_ver.kv_lens),
                                  np.asarray(c_seq.kv_lens))


# ---------------------------------------------------------------------------
# The serving lane — parity, rollback, fallback, records.
# ---------------------------------------------------------------------------

def _golden(engine, trace):
    out = {}
    for item in trace:
        toks = engine.serve(jnp.asarray([item["prompt"]], jnp.int32),
                            gen_len=item["max_new_tokens"])
        out[item["req_id"]] = np.asarray(toks)[0].tolist()
    return out


def _serve_with_occupancy_check(se, trace):
    reqs = {}
    pending = sorted(trace, key=lambda t: t["arrival_iter"])
    it = 0
    stale = 0
    while pending or se.sched.has_work():
        still = []
        for item in pending:
            if item["arrival_iter"] > it:
                still.append(item)
                continue
            req, res = se.submit(item["prompt"], item["max_new_tokens"],
                                 priority=item.get("priority", 0),
                                 req_id=item["req_id"])
            assert res.name == "ADMITTED", res
            reqs[req.req_id] = req
        pending = still
        se.step()
        for r in se.sched.running():
            held = len(se.sched.allocator.pages(r.req_id))
            if held != -(-r.kv_len // se.page):
                stale += 1
        it += 1
        assert it < 10_000
    return reqs, stale


def test_spec_serving_token_parity_xla_with_preemption(ctx1, tiny):
    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    # Repetitive prompts (lookup drafting's traffic shape) + a pool
    # sized to force eviction while candidate windows are in flight.
    trace = [
        {"req_id": "sp-0", "arrival_iter": 0, "prompt": [3, 9] * 4,
         "max_new_tokens": 12, "priority": 1},
        {"req_id": "sp-1", "arrival_iter": 0, "prompt": [7] * 5,
         "max_new_tokens": 8},
        {"req_id": "sp-2", "arrival_iter": 1, "prompt": [11, 4] * 3,
         "max_new_tokens": 8},
    ]
    golden = _golden(engine, trace)
    se = ServingEngine(engine, max_batch=3, num_pages=7, prefill_chunk=4,
                       spec_k=2)
    reqs, stale = _serve_with_occupancy_check(se, trace)
    assert all(r.tokens == golden[rid] for rid, r in reqs.items()), \
        {rid: (r.tokens, golden[rid]) for rid, r in reqs.items()}
    assert any(r.preemptions > 0 for r in reqs.values()), \
        "pool sizing no longer forces a preemption mid-spec"
    assert stale == 0, "rollback left pages beyond the accepted prefix"
    assert se.sched.allocator.free_count == se.sched.allocator.usable_pages
    assert sum(r.drafted_tokens for r in reqs.values()) > 0
    assert sum(r.accepted_draft_tokens for r in reqs.values()) > 0, \
        "nothing accepted — the lane degenerated to one-token decode"
    assert not se._spec_fallback


def test_spec_serving_accepts_multiple_tokens_per_step(ctx1, tiny):
    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    trace = [{"req_id": "cyc-0", "arrival_iter": 0,
              "prompt": [3, 9, 3, 9, 3, 9], "max_new_tokens": 24}]
    golden = _golden(engine, trace)
    se = ServingEngine(engine, max_batch=2, num_pages=16, prefill_chunk=4,
                       spec_k=3)
    reqs, _ = _serve_with_occupancy_check(se, trace)
    r = reqs["cyc-0"]
    assert r.tokens == golden["cyc-0"]
    # 24 tokens in strictly fewer decode iterations than one-token needs
    # — i.e. at least one step accepted more than one token.
    assert r.accepted_draft_tokens > 0


def test_spec_k0_keeps_the_one_token_path(ctx1, tiny):
    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    se = ServingEngine(engine, max_batch=2, prefill_chunk=4, spec_k=0)
    assert se._proposer is None and not se._spec_enabled()
    req, _ = se.submit([3, 9, 3, 9], 4)
    se.run()
    assert req.drafted_tokens == 0 and req.accepted_draft_tokens == 0
    assert ("verify", 1) not in se._jits


def test_spec_fallback_on_transient_verify_fault(ctx1, tiny):
    from triton_distributed_tpu.resilience import FaultInjectionError

    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    trace = [
        {"req_id": "fb-0", "arrival_iter": 0, "prompt": [3, 9] * 4,
         "max_new_tokens": 8},
        {"req_id": "fb-1", "arrival_iter": 0, "prompt": [7] * 5,
         "max_new_tokens": 6},
    ]
    golden = _golden(engine, trace)
    se = ServingEngine(engine, max_batch=2, num_pages=16, prefill_chunk=4,
                       spec_k=2)
    real = se._verify_jit
    fired = {"n": 0}

    def faulty():
        fn = real()

        def wrapper(*a, **kw):
            if fired["n"] == 0:
                fired["n"] += 1
                raise FaultInjectionError("test: verify fault")
            return fn(*a, **kw)

        return wrapper

    se._verify_jit = faulty
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        reqs, _ = _serve_with_occupancy_check(se, trace)
    assert fired["n"] == 1
    assert se._spec_fallback, "verify fault did not fall back"
    assert all(r.tokens == golden[rid] for rid, r in reqs.items())


def test_spec_nontransient_verify_error_propagates(ctx1, tiny):
    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    se = ServingEngine(engine, max_batch=2, prefill_chunk=4, spec_k=2)

    def boom():
        def wrapper(*a, **kw):
            raise ValueError("not transient")

        return wrapper

    se._verify_jit = boom
    se.submit([3, 9, 3, 9], 6)
    with pytest.raises(ValueError, match="not transient"):
        se.run()


def test_spec_config_validation(ctx1, tiny):
    from triton_distributed_tpu.serving.loop import ServingConfigError

    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    with pytest.raises(ServingConfigError, match="spec_k"):
        ServingEngine(engine, max_batch=2, spec_k=-1)


def test_spec_metrics_and_report_gate(ctx1, tiny, tmp_path):
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import report as obs_report

    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    run_dir = str(tmp_path / "spec-run")
    obs.start_run(run_dir)
    try:
        se = ServingEngine(engine, max_batch=2, num_pages=16,
                           prefill_chunk=4, spec_k=2)
        se.submit([3, 9] * 4, 10, req_id="m-0")
        se.run()
        snap = obs_metrics.registry().snapshot()
    finally:
        obs.finish_run()
    assert obs_metrics.SPEC_DRAFT_TOKENS in snap
    assert obs_metrics.SPEC_ACCEPTED_TOKENS in snap
    assert obs_metrics.SPEC_ACCEPT_RATE in snap
    assert snap[obs_metrics.SPEC_DRAFT_TOKENS]["value"] > 0
    # The report renders the spec lane and --check passes the snapshot.
    rc = obs_report.main([run_dir, "--check"])
    assert rc == 0


def test_spec_serving_token_parity_disagg(tiny):
    """Spec decode composes with the disaggregated tier: drafting starts
    only after a request is RUNNING on the decode role, so the KV
    migration stream never sees draft state — parity must hold across
    the full prefill → migrate → spec-decode round-trip."""
    from triton_distributed_tpu.disagg import (
        DisaggServingEngine, role_contexts,
    )

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 virtual CPU devices")
    cfg, params = tiny
    pctx, dctx = role_contexts(jax.devices()[:2])
    pe = Engine(cfg, params, pctx, backend="xla", max_seq=64)
    de = Engine(cfg, params, dctx, backend="xla", max_seq=64, page_size=4)
    oracle = Engine(cfg, params, pctx, backend="xla", max_seq=64,
                    page_size=4)
    trace = [
        {"req_id": "dsp-0", "arrival_iter": 0, "prompt": [3, 9] * 4,
         "max_new_tokens": 10, "priority": 1},
        {"req_id": "dsp-1", "arrival_iter": 1, "prompt": [7] * 6,
         "max_new_tokens": 6},
    ]
    golden = _golden(oracle, trace)
    se = DisaggServingEngine(pe, de, max_batch=2, num_pages=8,
                             prefill_chunk=4, block_pages=1, spec_k=2)
    reqs, stale = _serve_with_occupancy_check(se, trace)
    assert se.disagg_active, se.demotion_reason
    assert all(r.tokens == golden[rid] for rid, r in reqs.items()), \
        {rid: (r.tokens, golden[rid]) for rid, r in reqs.items()}
    assert len(se.migrations_log) >= 2
    assert stale == 0
    assert sum(r.drafted_tokens for r in reqs.values()) > 0


# ---------------------------------------------------------------------------
# The megakernel draft-and-verify lane.
# ---------------------------------------------------------------------------

MK_CFG = ModelConfig(hidden_size=256, intermediate_size=256, num_layers=2,
                     num_heads=2, num_kv_heads=1, head_dim=128,
                     vocab_size=512, qk_norm=True, dtype="float32")


def test_spec_window_program_validation():
    from triton_distributed_tpu.megakernel.models import build_decode_step

    kw = dict(hidden=256, hq_local=2, hkv_local=1, ffn_local=256,
              num_layers=1, max_seq=256, pos=255)
    with pytest.raises(ValueError, match="pool form"):
        build_decode_step(spec_window=2, **kw)
    with pytest.raises(ValueError, match="out of range"):
        build_decode_step(spec_window=200, paged=True,
                          inkernel_append=True, batch=128,
                          kv_pool_pages=3, table_pages=2, **kw)


def test_spec_serving_token_parity_megakernel(ctx1):
    params = init_dense_llm(jax.random.PRNGKey(1), MK_CFG)
    rng = np.random.default_rng(9)
    pat = rng.integers(0, 512, 7).tolist()
    trace = [
        {"req_id": "mksp-0", "arrival_iter": 0,
         "prompt": (pat * 19)[:126], "max_new_tokens": 8, "priority": 1},
        {"req_id": "mksp-1", "arrival_iter": 0,
         "prompt": (pat * 16)[:100], "max_new_tokens": 6},
    ]
    oracle = Engine(MK_CFG, params, ctx1, backend="xla", max_seq=256)
    golden = _golden(oracle, trace)
    eng = Engine(MK_CFG, params, ctx1, backend="megakernel", max_seq=256,
                 page_size=128)
    se = ServingEngine(eng, max_batch=2, num_pages=2, prefill_chunk=128,
                       spec_k=2)
    assert se._mk is not None and se._mk.spec_w == 3
    reqs, stale = _serve_with_occupancy_check(se, trace)
    assert se._mk is not None and eng.backend == "megakernel", \
        "megakernel spec lane silently demoted"
    assert all(r.tokens == golden[rid] for rid, r in reqs.items()), \
        {rid: (r.tokens, golden[rid]) for rid, r in reqs.items()}
    assert any(r.preemptions > 0 for r in reqs.values())
    assert stale == 0
    assert sum(r.drafted_tokens for r in reqs.values()) > 0
