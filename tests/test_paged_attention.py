"""Paged-KV attention decode (reference PagedKVCache + paged FA task,
SURVEY.md §2.7) — append + attention vs numpy golden, ragged lengths."""

import numpy as np

import jax.numpy as jnp

from triton_distributed_tpu.ops.paged_attention import (
    init_paged_kv_cache, paged_append, paged_decode_attention,
    paged_decode_attention_golden,
)


def _filled_cache(rng, b, page, max_pages, hkv, d, lens, num_pages=None):
    num_pages = num_pages or b * max_pages
    cache = init_paged_kv_cache(b, num_pages=num_pages, page_size=page,
                                num_kv_heads=hkv, head_dim=d,
                                max_pages=max_pages)
    kp = np.array(cache.k_pool)
    vp = np.array(cache.v_pool)
    table = np.asarray(cache.page_table)
    for i, n_tok in enumerate(lens):
        for t in range(n_tok):
            pid, row = table[i, t // page], t % page
            kp[pid, row] = rng.standard_normal((hkv, d)) * 0.3
            vp[pid, row] = rng.standard_normal((hkv, d)) * 0.3
    return cache._replace(k_pool=jnp.asarray(kp), v_pool=jnp.asarray(vp),
                          kv_lens=jnp.asarray(np.asarray(lens), jnp.int32))


def test_paged_decode_vs_golden(ctx):
    b, page, max_pages, hq, hkv, d = 4, 16, 4, 8, 4, 32
    rng = np.random.default_rng(0)
    lens = [64, 17, 1, 40]   # full, mid-page, single token, multi-page
    cache = _filled_cache(rng, b, page, max_pages, hkv, d, lens)
    q = jnp.asarray(rng.standard_normal((b, hq, d)) * 0.3, jnp.float32)

    out = paged_decode_attention(q, cache)
    ref = paged_decode_attention_golden(q, cache)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_paged_append_then_decode(ctx):
    """Append tokens one step at a time (the serving loop), then attend."""
    b, page, max_pages, hq, hkv, d = 2, 8, 3, 4, 2, 32
    rng = np.random.default_rng(1)
    cache = init_paged_kv_cache(b, num_pages=b * max_pages, page_size=page,
                                num_kv_heads=hkv, head_dim=d,
                                max_pages=max_pages)
    appended = []
    for _step in range(page + 3):   # crosses a page boundary
        k_new = jnp.asarray(rng.standard_normal((b, hkv, d)) * 0.3,
                            jnp.float32)
        v_new = jnp.asarray(rng.standard_normal((b, hkv, d)) * 0.3,
                            jnp.float32)
        cache = paged_append(cache, k_new, v_new)
        appended.append((np.asarray(k_new), np.asarray(v_new)))
    assert int(cache.kv_lens[0]) == page + 3

    q = jnp.asarray(rng.standard_normal((b, hq, d)) * 0.3, jnp.float32)
    out = paged_decode_attention(q, cache)
    ref = paged_decode_attention_golden(q, cache)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)

    # The appended rows landed where the table says they should.
    table = np.asarray(cache.page_table)
    kp = np.asarray(cache.k_pool)
    for t, (k_new, _) in enumerate(appended):
        np.testing.assert_allclose(kp[table[0, t // page], t % page],
                                   k_new[0])


def test_paged_shared_pool_page_reuse(ctx):
    """Two sequences can point at the SAME pool page (prefix sharing) —
    the table is data, not layout."""
    b, page, max_pages, hq, hkv, d = 2, 8, 2, 4, 2, 32
    rng = np.random.default_rng(2)
    cache = _filled_cache(rng, b, page, max_pages, hkv, d, [8, 8],
                          num_pages=b * max_pages)
    # Point sequence 1's first page at sequence 0's.
    table = np.asarray(cache.page_table).copy()
    table[1, 0] = table[0, 0]
    cache = cache._replace(page_table=jnp.asarray(table))

    q = jnp.asarray(rng.standard_normal((b, hq, d)) * 0.3, jnp.float32)
    out = paged_decode_attention(q, cache)
    ref = paged_decode_attention_golden(q, cache)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)