"""Multi-axis (2-D torus) ICI collectives on a (2, 4) CPU mesh — one
Pallas kernel driving both mesh axes (ops/multi_axis.py; the analog of the
reference's 2-D NUMA-aware rings, kernels/nvidia/allgather.py:140-378)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops.multi_axis import (
    all_gather_torus,
    all_reduce_torus,
    reduce_scatter_torus,
)
from triton_distributed_tpu.runtime.context import initialize_distributed


@pytest.fixture(scope="module")
def ctx24():
    """(x=2, y=4) torus mesh over the 8 virtual CPU devices."""
    return initialize_distributed(mesh_shape=(2, 4), axis_names=("x", "y"))


@pytest.fixture(scope="module")
def ctx81():
    """(x=8, y=1): the single-axis-degenerate contract."""
    return initialize_distributed(mesh_shape=(8, 1), axis_names=("x", "y"))


def test_all_gather_torus_golden(ctx24):
    N, m, cols = 8, 16, 128
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((N * m, cols)), jnp.float32)
    out = all_gather_torus(x, ctx24)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_all_gather_torus_bf16(ctx24):
    N, m, cols = 8, 16, 256
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((N * m, cols)), jnp.bfloat16)
    out = all_gather_torus(x, ctx24)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


@pytest.mark.parametrize("method", ["one_shot", "two_shot"])
def test_all_reduce_torus_golden(ctx24, method):
    n0, n1, m, cols = 2, 4, 32, 128
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((n0, n1, m, cols)), jnp.float32)
    out = all_reduce_torus(x, ctx24, method=method)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(x).sum((0, 1)),
                               rtol=1e-4, atol=1e-4)


def test_reduce_scatter_torus_golden(ctx24):
    n0, n1, mo, cols = 2, 4, 16, 128
    N = n0 * n1
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((n0, n1, N * mo, cols)),
                    jnp.float32)
    out = reduce_scatter_torus(x, ctx24)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(x).sum((0, 1)),
                               rtol=1e-4, atol=1e-4)


def test_all_gather_torus_degenerate_axis(ctx81):
    """n1 == 1 must fall back to the 1-D ring and still be correct."""
    N, m, cols = 8, 8, 128
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((N * m, cols)), jnp.float32)
    out = all_gather_torus(x, ctx81)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_all_reduce_torus_degenerate_axis(ctx81):
    n0, n1, m, cols = 8, 1, 16, 128
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((n0, n1, m, cols)), jnp.float32)
    out = all_reduce_torus(x, ctx81)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(x).sum((0, 1)),
                               rtol=1e-4, atol=1e-4)


def test_single_axis_entry_points_dispatch_tuple_axis(ctx24):
    """ops.all_gather_local / all_reduce_local / reduce_scatter_local accept
    a tuple axis and route to the torus kernels (the AUTO hook for layers
    running on ≥2-D ICI meshes)."""
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.ops.allgather import all_gather_local
    from triton_distributed_tpu.ops.allreduce import all_reduce_local
    from triton_distributed_tpu.runtime.context import shard_map_on

    N, m, cols = 8, 8, 128
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((N * m, cols)), jnp.float32)

    def ag(xl):
        return all_gather_local(xl, axis=("x", "y"), num_ranks=(2, 4))

    out = jax.jit(shard_map_on(ctx24, ag, P(("x", "y")), P(None)))(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    def ar(xl):
        return all_reduce_local(xl, axis=("x", "y"), num_ranks=(2, 4))

    y = jnp.asarray(rng.standard_normal((N, m, cols)), jnp.float32)
    out = jax.jit(shard_map_on(
        ctx24, lambda yl: ar(yl[0]),
        P(("x", "y")), P(None)))(y)
    np.testing.assert_allclose(np.asarray(out), np.asarray(y).sum(0),
                               rtol=1e-4, atol=1e-4)
