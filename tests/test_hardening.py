"""Robustness lanes (reference test strategy, SURVEY.md §4): straggler
injection, race-detection interpreter lane, non-divisible shapes, physical
ring construction, and profiler-trace evidence."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops import ag_gemm
from triton_distributed_tpu.ops.allgather_gemm import AGGemmConfig
from triton_distributed_tpu.ops.gemm import pallas_matmul
from triton_distributed_tpu.runtime.topology import (
    Topology, ici_ring_order, _is_torus_neighbor,
)
from triton_distributed_tpu.runtime.utils import group_profile


def _fake_topo(dims):
    coords = [()]
    for d in dims:
        coords = [c + (i,) for c in coords for i in range(d)]
    coords = sorted(coords)
    return Topology(num_devices=len(coords), platform="tpu",
                    coords=tuple(coords), num_processes=1,
                    devices_per_process=len(coords), is_multi_host=False)


@pytest.mark.parametrize("dims", [(8,), (2, 4), (4, 2), (2, 2, 2), (3, 4)])
def test_ici_ring_order_is_neighbor_cycle(dims):
    topo = _fake_topo(dims)
    order = ici_ring_order(topo)
    assert order is not None, dims
    assert sorted(order) == list(range(topo.num_devices))
    coords = topo.coords
    for a, b in zip(order, order[1:] + order[:1]):
        assert _is_torus_neighbor(coords[a], coords[b], dims), (
            dims, coords[a], coords[b])


def test_ici_ring_order_declines_gracefully():
    # Odd×odd grid has no Hamiltonian neighbor cycle; logical order keeps.
    assert ici_ring_order(_fake_topo((3, 3))) is None
    # Off-TPU topology (no coords).
    topo = Topology(8, "cpu", None, 1, 8, False)
    assert ici_ring_order(topo) is None


def test_ag_gemm_with_straggler(ctx):
    """A delayed producer must not change results — only timing (reference
    stress_test_ag_gemm straggler sweep)."""
    n, m, k, cols = 8, 16, 128, 128
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n * m, k)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n * cols)) * 0.1, jnp.float32)
    ref = np.asarray(a) @ np.asarray(b)
    for s_rank in (0, 3):
        cfg = AGGemmConfig(straggler=(s_rank, 5000))
        out = ag_gemm(a, b, ctx, cfg=cfg)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3,
                                   atol=1e-3)


def test_ag_gemm_random_shape_sweep(ctx):
    """Random M sweep (reference stress_test_ag_gemm.py:55-121)."""
    n, k, cols = 8, 128, 128
    rng = np.random.default_rng(1)
    for m in rng.choice([8, 16, 24, 40], size=3, replace=False):
        a = jnp.asarray(rng.standard_normal((n * int(m), k)) * 0.1,
                        jnp.float32)
        b = jnp.asarray(rng.standard_normal((k, n * cols)) * 0.1, jnp.float32)
        out = ag_gemm(a, b, ctx)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(a) @ np.asarray(b),
                                   rtol=1e-3, atol=1e-3, err_msg=f"m={m}")


def test_pallas_matmul_odd_shapes():
    """pick_tile fallback shapes (whole-dim tiles) must stay correct."""
    rng = np.random.default_rng(2)
    for (m, k, cols) in [(20, 256, 384), (8, 136, 128), (24, 128, 136)]:
        a = jnp.asarray(rng.standard_normal((m, k)) * 0.3, jnp.float32)
        b = jnp.asarray(rng.standard_normal((k, cols)) * 0.3, jnp.float32)
        out = pallas_matmul(a, b)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(a) @ np.asarray(b),
            rtol=1e-3, atol=1e-3, err_msg=f"{(m, k, cols)}")


RACE_LANE = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["TDTPU_DETECT_RACES"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, jax.numpy as jnp
import triton_distributed_tpu as tdt
from triton_distributed_tpu.ops import ag_gemm
ctx = tdt.initialize_distributed(axis_names=("tp",))
rng = np.random.default_rng(0)
a = jnp.asarray(rng.standard_normal((4 * 8, 128)) * 0.1, jnp.float32)
b = jnp.asarray(rng.standard_normal((128, 4 * 128)) * 0.1, jnp.float32)
out = ag_gemm(a, b, ctx)
np.testing.assert_allclose(np.asarray(out), np.asarray(a) @ np.asarray(b),
                           rtol=1e-3, atol=1e-3)
print("RACE_LANE_OK")
"""


def test_race_detection_lane():
    """Run AG+GEMM under the interpreter's race detector in a fresh process
    (TDTPU_DETECT_RACES=1 changes interpreter scheduling; reference analog:
    the compute-sanitizer hook, scripts/launch.sh:160-163)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-c", RACE_LANE], capture_output=True, text=True,
        timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert "RACE_LANE_OK" in res.stdout, res.stdout + res.stderr
    assert "race" not in res.stderr.lower().replace(
        "detect_races", ""), res.stderr


def test_group_profile_produces_trace(ctx, tmp_path):
    """The profiler context must emit a Perfetto trace for an overlapped op
    (VERDICT r1: group_profile had never produced a trace)."""
    n, m, k, cols = 8, 16, 128, 128
    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.standard_normal((n * m, k)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n * cols)), jnp.float32)
    with group_profile("ag_gemm_trace", do_prof=True,
                       log_dir=str(tmp_path)):
        jax.block_until_ready(ag_gemm(a, b, ctx))
    produced = [p for p in (tmp_path / "ag_gemm_trace").rglob("*")
                if p.is_file()]
    assert produced, "no trace files written"

    # Merge (the reference's rank-0 _merge_json step): treat the same dir
    # as two "hosts" and check the combined chrome trace loads.
    import gzip
    import json

    from triton_distributed_tpu.runtime.utils import merge_profiles

    out = tmp_path / "merged.trace.json.gz"
    n = merge_profiles([str(tmp_path / "ag_gemm_trace")] * 2, str(out))
    assert n == 2
    with gzip.open(out, "rt") as f:
        data = json.load(f)
    assert data["traceEvents"], "merged trace has no events"
    pids = {e.get("pid") for e in data["traceEvents"]
            if isinstance(e.get("pid"), int)}
    assert any(p >= 200_000 for p in pids), "second source pids not offset"


def test_collectives_random_shape_sweep(ctx):
    """Random (rows, cols) sweep over RS/AR (reference stress pattern:
    sweep shapes for many iterations to catch shape-dependent bugs)."""
    from triton_distributed_tpu.ops import all_reduce, reduce_scatter

    n = 8
    rng = np.random.default_rng(4)
    for _ in range(3):
        m = int(rng.choice([8, 16, 24]))
        cols = int(rng.choice([128, 256, 384]))
        xs = rng.standard_normal((n, m, cols)).astype(np.float32)
        out = all_reduce(jnp.asarray(xs), ctx)
        np.testing.assert_allclose(np.asarray(out), xs.sum(0),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"AR {m}x{cols}")
        xr = rng.standard_normal((n, n * m, cols)).astype(np.float32)
        out = reduce_scatter(jnp.asarray(xr), ctx)
        np.testing.assert_allclose(np.asarray(out), xr.sum(0),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=f"RS {m}x{cols}")


def test_a2a_random_splits_sweep(ctx):
    """Random split matrices incl. degenerate rows (reference stress)."""
    from triton_distributed_tpu.ops import fast_all_to_all

    n, epr, cap, hidden = 8, 2, 32, 128
    rng = np.random.default_rng(5)
    for trial in range(2):
        splits = rng.integers(0, cap // n, size=(n, n, epr)).astype(np.int32)
        splits[trial % n] = 0  # one device sends nothing
        send = np.zeros((n, n, cap, hidden), np.float32)
        for d_ in range(n):
            for p_ in range(n):
                r_ = int(splits[d_, p_].sum())
                send[d_, p_, :r_] = rng.standard_normal((r_, hidden))
        recv, rsplits = fast_all_to_all(jnp.asarray(send),
                                        jnp.asarray(splits), ctx)
        rsplits = np.asarray(rsplits)
        np.testing.assert_array_equal(rsplits, np.swapaxes(splits, 0, 1))
        recv = np.asarray(recv)
        for d_ in range(n):
            for p_ in range(n):
                r_ = int(rsplits[d_, p_].sum())
                np.testing.assert_allclose(
                    recv[d_, p_, :r_], send[p_, d_, :r_],
                    err_msg=f"payload recv[{d_},{p_}]")


def test_engine_serve_profile(ctx, tmp_path):
    """Engine.serve(profile_dir=...) must emit a decode trace (reference
    Engine profile mode, engine.py:153-179)."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.config import tiny_config

    eng = AutoLLM.from_config(tiny_config(), ctx=ctx, max_seq=16)
    out = eng.serve(jnp.asarray([[1, 2, 3]], jnp.int32), gen_len=3,
                    profile_dir=str(tmp_path))
    assert out.shape == (1, 3)
    files = [p for p in (tmp_path / "decode").rglob("*") if p.is_file()]
    assert files, "no profiler trace emitted"


def test_gemm_rs_with_straggler(ctx):
    """Straggler parity for the role-inverted kernel (reference injects on
    allreduce/RS paths too, allreduce.py:137)."""
    from triton_distributed_tpu.ops import gemm_rs
    from triton_distributed_tpu.ops.gemm_reduce_scatter import GemmRSConfig

    n, m, k, cols = 8, 64, 32, 128
    rng = np.random.default_rng(6)
    a = jnp.asarray(rng.standard_normal((m, n * k)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((n * k, cols)) * 0.1, jnp.float32)
    ref = np.asarray(a) @ np.asarray(b)
    for s_rank in (0, 5):
        out = gemm_rs(a, b, ctx, cfg=GemmRSConfig(straggler=(s_rank, 5000)))
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)
