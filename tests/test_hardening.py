"""Robustness lanes (reference test strategy, SURVEY.md §4): straggler
injection, race-detection interpreter lane, non-divisible shapes, physical
ring construction, and profiler-trace evidence."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops import ag_gemm
from triton_distributed_tpu.ops.allgather_gemm import AGGemmConfig
from triton_distributed_tpu.ops.gemm import pallas_matmul
from triton_distributed_tpu.runtime.topology import (
    Topology, ici_ring_order, _is_torus_neighbor,
)
from triton_distributed_tpu.runtime.utils import group_profile


def _fake_topo(dims):
    coords = [()]
    for d in dims:
        coords = [c + (i,) for c in coords for i in range(d)]
    coords = sorted(coords)
    return Topology(num_devices=len(coords), platform="tpu",
                    coords=tuple(coords), num_processes=1,
                    devices_per_process=len(coords), is_multi_host=False)


@pytest.mark.parametrize("dims", [(8,), (2, 4), (4, 2), (2, 2, 2), (3, 4)])
def test_ici_ring_order_is_neighbor_cycle(dims):
    topo = _fake_topo(dims)
    order = ici_ring_order(topo)
    assert order is not None, dims
    assert sorted(order) == list(range(topo.num_devices))
    coords = topo.coords
    for a, b in zip(order, order[1:] + order[:1]):
        assert _is_torus_neighbor(coords[a], coords[b], dims), (
            dims, coords[a], coords[b])


def test_ici_ring_order_declines_gracefully():
    # Odd×odd grid has no Hamiltonian neighbor cycle; logical order keeps.
    assert ici_ring_order(_fake_topo((3, 3))) is None
    # Off-TPU topology (no coords).
    topo = Topology(8, "cpu", None, 1, 8, False)
    assert ici_ring_order(topo) is None


def test_ag_gemm_with_straggler(ctx):
    """A delayed producer must not change results — only timing (reference
    stress_test_ag_gemm straggler sweep)."""
    n, m, k, cols = 8, 16, 128, 128
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((n * m, k)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n * cols)) * 0.1, jnp.float32)
    ref = np.asarray(a) @ np.asarray(b)
    for s_rank in (0, 3):
        cfg = AGGemmConfig(straggler=(s_rank, 5000))
        out = ag_gemm(a, b, ctx, cfg=cfg)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3,
                                   atol=1e-3)


def test_ag_gemm_random_shape_sweep(ctx):
    """Random M sweep (reference stress_test_ag_gemm.py:55-121)."""
    n, k, cols = 8, 128, 128
    rng = np.random.default_rng(1)
    for m in rng.choice([8, 16, 24, 40], size=3, replace=False):
        a = jnp.asarray(rng.standard_normal((n * int(m), k)) * 0.1,
                        jnp.float32)
        b = jnp.asarray(rng.standard_normal((k, n * cols)) * 0.1, jnp.float32)
        out = ag_gemm(a, b, ctx)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(a) @ np.asarray(b),
                                   rtol=1e-3, atol=1e-3, err_msg=f"m={m}")


def test_pallas_matmul_odd_shapes():
    """pick_tile fallback shapes (whole-dim tiles) must stay correct."""
    rng = np.random.default_rng(2)
    for (m, k, cols) in [(20, 256, 384), (8, 136, 128), (24, 128, 136)]:
        a = jnp.asarray(rng.standard_normal((m, k)) * 0.3, jnp.float32)
        b = jnp.asarray(rng.standard_normal((k, cols)) * 0.3, jnp.float32)
        out = pallas_matmul(a, b)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(a) @ np.asarray(b),
            rtol=1e-3, atol=1e-3, err_msg=f"{(m, k, cols)}")


RACE_LANE = r"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["TDTPU_DETECT_RACES"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, jax.numpy as jnp
import triton_distributed_tpu as tdt
from triton_distributed_tpu.ops import ag_gemm
ctx = tdt.initialize_distributed(axis_names=("tp",))
rng = np.random.default_rng(0)
a = jnp.asarray(rng.standard_normal((4 * 8, 128)) * 0.1, jnp.float32)
b = jnp.asarray(rng.standard_normal((128, 4 * 128)) * 0.1, jnp.float32)
out = ag_gemm(a, b, ctx)
np.testing.assert_allclose(np.asarray(out), np.asarray(a) @ np.asarray(b),
                           rtol=1e-3, atol=1e-3)
print("RACE_LANE_OK")
"""


def test_race_detection_lane():
    """Run AG+GEMM under the interpreter's race detector in a fresh process
    (TDTPU_DETECT_RACES=1 changes interpreter scheduling; reference analog:
    the compute-sanitizer hook, scripts/launch.sh:160-163)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-c", RACE_LANE], capture_output=True, text=True,
        timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert "RACE_LANE_OK" in res.stdout, res.stdout + res.stderr
    assert "race" not in res.stderr.lower().replace(
        "detect_races", ""), res.stderr


def test_group_profile_produces_trace(ctx, tmp_path):
    """The profiler context must emit a Perfetto trace for an overlapped op
    (VERDICT r1: group_profile had never produced a trace)."""
    n, m, k, cols = 8, 16, 128, 128
    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.standard_normal((n * m, k)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n * cols)), jnp.float32)
    with group_profile("ag_gemm_trace", do_prof=True,
                       log_dir=str(tmp_path)):
        jax.block_until_ready(ag_gemm(a, b, ctx))
    produced = [p for p in (tmp_path / "ag_gemm_trace").rglob("*")
                if p.is_file()]
    assert produced, "no trace files written"

    # Merge (the reference's rank-0 _merge_json step): treat the same dir
    # as two "hosts" and check the combined chrome trace loads.
    import gzip
    import json

    from triton_distributed_tpu.runtime.utils import merge_profiles

    out = tmp_path / "merged.trace.json.gz"
    n = merge_profiles([str(tmp_path / "ag_gemm_trace")] * 2, str(out))
    assert n == 2
    with gzip.open(out, "rt") as f:
        data = json.load(f)
    assert data["traceEvents"], "merged trace has no events"
    pids = {e.get("pid") for e in data["traceEvents"]
            if isinstance(e.get("pid"), int)}
    assert any(p >= 200_000 for p in pids), "second source pids not offset"
