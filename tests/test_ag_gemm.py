"""Overlapped AG+GEMM / GEMM+RS correctness vs XLA goldens.

Reference pattern: test_ag_gemm.py / test_gemm_rs.py compare against
torch.distributed all_gather + matmul goldens with inputs mutated across
iterations (test_ag_gemm.py:86-92)."""

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops import ag_gemm, gemm_rs


def _rand(shape, dtype=jnp.float32, seed=0, scale=0.1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape) * scale, dtype=dtype)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_ag_gemm(ctx, dtype):
    n = ctx.num_ranks
    m, k, ncols = 16, 128, 128  # per-device A rows / inner / B cols
    tol = dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else dict(rtol=1e-4, atol=1e-5)
    for it in range(2):
        a = _rand((n * m, k), dtype, seed=it)
        b = _rand((k, n * ncols), dtype, seed=100 + it)
        got = ag_gemm(a, b, ctx)
        expected = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
        np.testing.assert_allclose(np.asarray(got, np.float32), expected, **tol)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_gemm_rs(ctx, dtype):
    n = ctx.num_ranks
    m, k, ncols = 64, 32, 128  # total rows / per-device k / B cols
    tol = dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else dict(rtol=1e-4, atol=1e-5)
    for it in range(2):
        a = _rand((m, n * k), dtype, seed=it)
        b = _rand((n * k, ncols), dtype, seed=200 + it)
        got = gemm_rs(a, b, ctx)
        expected = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
        np.testing.assert_allclose(np.asarray(got, np.float32), expected, **tol)


def test_ag_gemm_shape_errors(ctx):
    with pytest.raises((ValueError, TypeError)):
        ag_gemm(jnp.ones((8 * 16, 64)), jnp.ones((128, 8 * 16)), ctx)


def test_pallas_matmul_fp8():
    """float8_e4m3fn GEMM lane: fp8 operands, fp32 accumulation, bf16 out
    — matches the upcast golden exactly (the fp8 values are exact in
    bf16/f32, so the MXU accumulation is the only rounding source)."""
    from triton_distributed_tpu.ops.gemm import pallas_matmul

    rng = np.random.default_rng(11)
    a = jnp.asarray(rng.standard_normal((64, 128)), jnp.float8_e4m3fn)
    b = jnp.asarray(rng.standard_normal((128, 256)), jnp.float8_e4m3fn)
    out = pallas_matmul(a, b, out_dtype=jnp.float32)
    gold = np.asarray(a.astype(jnp.float32)) @ np.asarray(
        b.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out), gold, rtol=1e-5, atol=1e-5)


def test_ag_gemm_sub_chunk_odd_rows(ctx):
    """Sub-chunked consumer with shard rows whose default tile does not
    divide the sub-block (m=1152: pick_tile(m)=384, m_sub=576) — the
    round-4 review's row-drop scenario. Every output row must be real."""
    from triton_distributed_tpu.ops.allgather_gemm import AGGemmConfig

    n, m, k, nc = 8, 1152, 128, 128
    rng = np.random.default_rng(13)
    a = jnp.asarray(rng.standard_normal((n * m, k)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n * nc)) * 0.1, jnp.float32)
    out = ag_gemm(a, b, ctx, cfg=AGGemmConfig(sub_chunks=2))
    gold = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
    np.testing.assert_allclose(np.asarray(out), gold, rtol=2e-4, atol=2e-4)


def test_pallas_matmul_mixed_bf16_fp8():
    """The realistic fp8 configuration: bf16 activations x e4m3 weights
    (upcast in VMEM), bf16 out — matches the quantized-weight golden; a
    low-precision A with wider B is rejected (it would silently quantize
    the weights)."""
    from triton_distributed_tpu.ops.gemm import pallas_matmul

    rng = np.random.default_rng(17)
    a = jnp.asarray(rng.standard_normal((64, 128)), jnp.bfloat16)
    b8 = jnp.asarray(rng.standard_normal((128, 256)) * 0.1,
                     jnp.float8_e4m3fn)
    out = pallas_matmul(a, b8, out_dtype=jnp.float32)
    gold = np.asarray(a.astype(jnp.float32)) @ np.asarray(
        b8.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out), gold, rtol=2e-2, atol=2e-2)

    with pytest.raises(ValueError, match="narrower"):
        pallas_matmul(a.astype(jnp.float8_e4m3fn), b8.astype(jnp.bfloat16))
