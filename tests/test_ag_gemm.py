"""Overlapped AG+GEMM / GEMM+RS correctness vs XLA goldens.

Reference pattern: test_ag_gemm.py / test_gemm_rs.py compare against
torch.distributed all_gather + matmul goldens with inputs mutated across
iterations (test_ag_gemm.py:86-92)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops import ag_gemm, gemm_rs


def _rand(shape, dtype=jnp.float32, seed=0, scale=0.1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape) * scale, dtype=dtype)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_ag_gemm(ctx, dtype):
    n = ctx.num_ranks
    m, k, ncols = 16, 128, 128  # per-device A rows / inner / B cols
    tol = dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else dict(rtol=1e-4, atol=1e-5)
    for it in range(2):
        a = _rand((n * m, k), dtype, seed=it)
        b = _rand((k, n * ncols), dtype, seed=100 + it)
        got = ag_gemm(a, b, ctx)
        expected = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
        np.testing.assert_allclose(np.asarray(got, np.float32), expected, **tol)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_gemm_rs(ctx, dtype):
    n = ctx.num_ranks
    m, k, ncols = 64, 32, 128  # total rows / per-device k / B cols
    tol = dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else dict(rtol=1e-4, atol=1e-5)
    for it in range(2):
        a = _rand((m, n * k), dtype, seed=it)
        b = _rand((n * k, ncols), dtype, seed=200 + it)
        got = gemm_rs(a, b, ctx)
        expected = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
        np.testing.assert_allclose(np.asarray(got, np.float32), expected, **tol)


def test_ag_gemm_shape_errors(ctx):
    with pytest.raises((ValueError, TypeError)):
        ag_gemm(jnp.ones((8 * 16, 64)), jnp.ones((128, 8 * 16)), ctx)
