"""Multi-replica fleet router (ISSUE 17, docs/fleet.md).

The load-bearing contracts: prefix-affinity routing from the shadow
index (never device probing), bounded spill chains ending in a NAMED
shed, drain-onto-siblings with token parity and first-submission TTFT
accounting, deterministic autoscale decisions, and per-replica
namespacing everywhere evidence lands (metrics labels, flight-dump
filenames, page-audit report names).
"""

import os

import numpy as np
import pytest

import jax

from triton_distributed_tpu.fleet import (
    AffinityIndex, AutoscaleConfigError, Autoscaler, FleetConfigError,
    FleetRouter, FleetShedError, ReplicaHandle,
)
from triton_distributed_tpu.models.config import tiny_config
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving import AdmitResult


@pytest.fixture(scope="module")
def tiny():
    """(cfg, params) shared by every fleet in this module."""
    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(7), cfg)
    return cfg, params


def _replica_engine(tiny, devices):
    cfg, params = tiny
    ctx = initialize_distributed(mesh_shape=(len(devices),),
                                 axis_names=("tp",), devices=devices)
    return Engine(cfg, params, ctx, backend="xla", max_seq=64,
                  page_size=4)


# Building an Engine recompiles its serve/prefill/decode jits, which
# dominates this module's wall clock. The serving tier's mutable state
# (scheduler, pools, prefix cache, registries) lives on ServingEngine,
# so 1-device Engines are reusable across tests — each fleet still gets
# DISTINCT Engine objects per replica. Struck (2-device) replicas are
# always built fresh: evacuation repartitions the Engine itself.
_ENGINE_POOL: list = []


def _pooled_engine(tiny, slot):
    while len(_ENGINE_POOL) <= slot:
        _ENGINE_POOL.append(_replica_engine(tiny, jax.devices()[:1]))
    return _ENGINE_POOL[slot]


def _fleet(tiny, n, *, struck=None, **kw):
    """n replicas; only ``struck`` gets a 2-device mesh, so a rank-1
    loss lands in exactly that replica's health ledger."""
    kw.setdefault("max_batch", 2)
    kw.setdefault("num_pages", 16)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("max_waiting", 8)
    kw.setdefault("prefix_cache", True)
    policy = kw.pop("policy", "affinity")
    strict_shed = kw.pop("strict_shed", False)
    autoscaler = kw.pop("autoscaler", None)
    reps = []
    for i in range(n):
        if i == struck:
            eng = _replica_engine(tiny, jax.devices()[:2])
        else:
            eng = _pooled_engine(tiny, i)
        reps.append(ReplicaHandle.build(str(i), eng, **kw))
    return FleetRouter(reps, policy=policy, strict_shed=strict_shed,
                       autoscaler=autoscaler)


_ORACLE = {}


def _golden(tiny, prompt, max_new):
    """Sequential-serve oracle; one engine per module (rebuilding one
    per call recompiles the serve path every time)."""
    import jax.numpy as jnp

    key = id(tiny)
    if key not in _ORACLE:
        _ORACLE.clear()
        _ORACLE[key] = _replica_engine(tiny, jax.devices()[:1])
    toks = _ORACLE[key].serve(jnp.asarray([prompt], jnp.int32),
                              gen_len=max_new)
    return np.asarray(toks)[0].tolist()


# ---------------------------------------------------------------------------
# AffinityIndex: the replica-coverage shadow.
# ---------------------------------------------------------------------------

def test_affinity_index_lcp_and_events():
    ix = AffinityIndex()
    ix.note("0", "insert", [1, 2, 3, 4])
    ix.note("1", "insert", [1, 2, 9])
    assert ix.match_len("0", [1, 2, 3, 7]) == 3
    assert ix.match_len("1", [1, 2, 3, 7]) == 2
    assert ix.match_len("1", [8, 8]) == 0
    # A hit refreshes coverage too (the replica proved it still holds it).
    ix.note("1", "hit", [5, 6])
    assert ix.match_len("1", [5, 6, 7]) == 2
    # invalidate drops the WHOLE replica's coverage (pool rebuilt).
    ix.note("1", "invalidate", None)
    assert ix.match_len("1", [1, 2]) == 0
    assert ix.match_len("0", [1, 2]) == 2
    assert ix.coverage("0") == 1 and ix.coverage("1") == 0


def test_affinity_index_bound_drop_and_bad_kind():
    ix = AffinityIndex(max_chains=2)
    for i in range(4):
        ix.note("0", "insert", [i, i + 1])
    assert ix.coverage("0") == 2          # recency-bounded, no growth
    assert ix.match_len("0", [0, 1]) == 0  # oldest chain evicted
    assert ix.match_len("0", [3, 4]) == 2
    ix.drop("0")
    assert ix.coverage("0") == 0
    with pytest.raises(ValueError, match="kind"):
        ix.note("0", "mystery", [1])


# ---------------------------------------------------------------------------
# Named configuration errors.
# ---------------------------------------------------------------------------

def test_fleet_config_errors():
    with pytest.raises(FleetConfigError, match="at least one replica"):
        FleetRouter([])
    rep = ReplicaHandle("0", se=None)
    with pytest.raises(FleetConfigError, match="not a ReplicaHandle"):
        FleetRouter([object()])
    with pytest.raises(FleetConfigError, match="duplicate replica id"):
        FleetRouter([rep, ReplicaHandle("0", se=None)])
    with pytest.raises(FleetConfigError, match="policy"):
        FleetRouter([rep], policy="random")
    with pytest.raises(FleetConfigError, match="max_spills"):
        FleetRouter([rep], max_spills=-1, clock=lambda: 0.0)


def test_autoscaler_config_errors():
    with pytest.raises(AutoscaleConfigError, match="min_replicas"):
        Autoscaler(min_replicas=0)
    with pytest.raises(AutoscaleConfigError, match="cooldown"):
        Autoscaler(cooldown=0)
    with pytest.raises(AutoscaleConfigError, match="queue_high"):
        Autoscaler(queue_high=0)
    with pytest.raises(AutoscaleConfigError, match="shrink_margin"):
        Autoscaler(shrink_margin=1.5)


def test_shed_error_is_named():
    e = FleetShedError("r-9", ["0", "1", "2"], 2)
    assert e.req_id == "r-9" and e.tried == ["0", "1", "2"]
    assert e.spills == 2
    assert "shed" in str(e) and "r-9" in str(e) and "3 candidate" in str(e)


# ---------------------------------------------------------------------------
# Routing: spread, affinity, spill/shed, retry accounting.
# ---------------------------------------------------------------------------

def test_cold_traffic_spreads_with_parity(tiny):
    from triton_distributed_tpu.serving.loadgen import run_trace

    router = _fleet(tiny, 3)
    trace = [
        {"req_id": f"c-{i}", "arrival_iter": 0,
         "prompt": [11 + 5 * i, 3, 77, 4 + i, 29, 6 + i],
         "max_new_tokens": 4, "priority": 0}
        for i in range(6)
    ]
    report = run_trace(router, [dict(t) for t in trace])
    reqs = {r.req_id: r for r in report.pop("requests")}
    assert report["all_finished"]
    assert router.routed == 6 and router.sheds == 0
    spread = [rid for rid, rep in sorted(router.replicas.items())
              if rep.routed > 0]
    assert len(spread) >= 2, spread
    for t in trace:
        assert reqs[t["req_id"]].tokens == _golden(
            tiny, t["prompt"], t["max_new_tokens"])
    desc = router.describe()
    assert desc["routed"] == 6 and desc["replicas_active"] == 3
    assert [row["replica"] for row in desc["replicas"]] == ["0", "1", "2"]


def test_affinity_routes_warm_to_holder(tiny):
    router = _fleet(tiny, 2)
    fam = [9, 9, 8, 7, 6, 5, 4, 3]
    req0, res0 = router.submit(fam, 3, req_id="warm-0")
    assert res0 is AdmitResult.ADMITTED
    router.run()
    # The cold serve fed insert events through the PrefixCache hook:
    # the shadow now advertises the family on exactly one replica.
    holder = [rid for rid in router.replicas
              if router.affinity.coverage(rid) > 0]
    assert len(holder) == 1
    req1, res1 = router.submit(fam[:6] + [99, 98], 3, req_id="warm-1")
    assert res1 is AdmitResult.ADMITTED
    assert router.affinity_hits == 1
    hit_rep = [rid for rid, rep in router.replicas.items()
               if rep.affinity_hits > 0]
    assert hit_rep == holder
    router.run()
    assert req1.state.name == "FINISHED"


def test_spill_then_named_shed_then_retry_accounting(tiny):
    router = _fleet(tiny, 2, max_batch=1, max_waiting=1, num_pages=4,
                    strict_shed=True)
    admitted = []
    shed_exc = None
    for i in range(8):
        try:
            rq, rs = router.submit([21 + i, 7, 3, 5 + i], 3,
                                   req_id=f"sp-{i}")
        except FleetShedError as e:
            shed_exc = e
            break
        assert rs is AdmitResult.ADMITTED
        admitted.append(rq)
    assert shed_exc is not None, "the fleet never saturated"
    assert shed_exc.req_id == f"sp-{len(admitted)}"
    assert sorted(shed_exc.tried) == ["0", "1"]   # full chain walked
    assert router.sheds == 1 and router.spills >= 1
    assert router.shed_log[-1]["req_id"] == shed_exc.req_id
    # Open-loop retry with the SAME req_id: TTFT counts from the FIRST
    # submission — the shed-and-retry wait must not vanish.
    first_try = router._first_try[shed_exc.req_id]
    router.run()
    rq, rs = router.submit([55, 56, 57, 58], 3, req_id=shed_exc.req_id)
    assert rs is AdmitResult.ADMITTED
    assert router.shed_retries == 1
    assert rq.t_arrival == first_try
    router.run()
    assert all(r.state.name == "FINISHED" for r in admitted)


# ---------------------------------------------------------------------------
# Drain / re-admit: the resilience composition.
# ---------------------------------------------------------------------------

def test_kill_one_replica_drains_onto_siblings_with_parity(tiny):
    import warnings

    from triton_distributed_tpu.resilience import faults

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 virtual CPU devices")
    rejoin_prev = os.environ.get("TDTPU_REJOIN_AFTER")
    os.environ["TDTPU_REJOIN_AFTER"] = "3"
    try:
        router = _fleet(tiny, 2, struck=1)
    finally:
        if rejoin_prev is None:
            os.environ.pop("TDTPU_REJOIN_AFTER", None)
        else:
            os.environ["TDTPU_REJOIN_AFTER"] = rejoin_prev
    trace = [
        {"req_id": f"dr-{i}",
         "prompt": [31 + 9 * i, 2, 64, 5 + i, 17, 3 + i],
         "max_new_tokens": 4} for i in range(4)
    ]
    reqs = {}
    for t in trace:
        rq, rs = router.submit(t["prompt"], t["max_new_tokens"],
                               req_id=t["req_id"])
        assert rs is AdmitResult.ADMITTED
        reqs[rq.req_id] = rq
    assert router.replicas["1"].routed > 0   # the victim holds work
    for _ in range(2):
        router.step()
    arrivals = {rid: r.t_arrival for rid, r in reqs.items()}
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            faults.mark_rank_lost(1)
            for _ in range(4):
                router.step()
            assert router.replicas["1"].draining
            assert router.drain_moves >= 1
            faults.clear_rank_loss(1)
            router.run()
    finally:
        faults.clear_rank_loss()
    for t in trace:
        r = reqs[t["req_id"]]
        assert r.state.name == "FINISHED"
        assert r.tokens == _golden(tiny, t["prompt"],
                                   t["max_new_tokens"]), t["req_id"]
        # First-submission accounting survives the cross-replica move.
        assert r.t_arrival == arrivals[t["req_id"]]
    assert router.drains == 1 and router.readmits == 1
    assert not router.replicas["1"].draining
    assert [e["event"] for e in router.fleet_log] == ["drain", "readmit"]


def test_manual_drain_is_idempotent_and_parks_overflow(tiny):
    router = _fleet(tiny, 2, max_batch=1, max_waiting=1, num_pages=4)
    for i in range(4):
        rq, rs = router.submit([41 + i, 6, 2, 9 + i], 3, req_id=f"mp-{i}")
        assert rs is AdmitResult.ADMITTED, f"mp-{i}: {rs}"
        if i == 1:
            router.step()   # move the first pair waiting -> active
    moved = router.drain("0", reason="manual")
    assert moved >= 1 and router.drains == 1
    assert router.drain("0") == 0 and router.drains == 1   # idempotent
    # Sibling capacity is 1+1: the overflow parks on the pending queue
    # (never dropped) and admits as slots free up.
    router.replicas["0"].draining = False   # manual re-admit for the run
    router.run()
    assert not router._pending


# ---------------------------------------------------------------------------
# Autoscaler decisions.
# ---------------------------------------------------------------------------

def test_autoscaler_shrinks_idle_then_grows_under_pressure(tiny):
    router = _fleet(tiny, 3, autoscaler=Autoscaler(
        min_replicas=1, cooldown=2, queue_high=1.0))
    router.submit([3, 1, 4, 1, 5], 2, req_id="as-0")
    router.run()
    auto = router.autoscaler
    assert auto.shrinks >= 1
    assert any(rep.scaled_out for rep in router.replicas.values())
    for i in range(8):
        router.submit([61 + 3 * i, 2, 8, 5 + i], 3, req_id=f"as-b{i}")
    router.run()
    assert auto.grows >= 1
    actions = [d["action"] for d in auto.log]
    assert "shrink" in actions
    assert "grow" in actions[actions.index("shrink"):]
    # Decisions are named and step-stamped (deterministic evidence).
    for d in auto.log:
        assert d["reason"] and isinstance(d["step"], int)


# ---------------------------------------------------------------------------
# Per-replica namespacing: metrics labels, page-audit names, flight ids.
# ---------------------------------------------------------------------------

def test_metrics_merge_publishes_replica_labels(tiny, tmp_path):
    from triton_distributed_tpu import obs as _obs

    _obs.start_run(str(tmp_path))
    try:
        router = _fleet(tiny, 2)
        for i in range(3):
            router.submit([71 + 7 * i, 4, 9, 2 + i], 3, req_id=f"mm-{i}")
        router.run()
        # run() publishes per step (delta-merged); one more explicit
        # publish must not double-count anything.
        router.publish_metrics()
        snap = obs_metrics.registry().snapshot()
    finally:
        _obs.finish_run()
    assert snap[obs_metrics.FLEET_ROUTED]["value"] == 3
    labeled = {k for k in snap if 'replica="' in k}
    assert any('replica="0"' in k for k in labeled)
    assert any('replica="1"' in k for k in labeled)
    finished = [k for k in labeled
                if k.startswith(obs_metrics.SERVE_FINISHED)]
    assert sum(snap[k]["value"] for k in finished) == 3
    assert snap[obs_metrics.FLEET_REPLICAS_ACTIVE]["value"] == 2


def test_page_audit_names_the_violating_replica(tiny, monkeypatch):
    monkeypatch.setenv("TDTPU_PAGE_AUDIT", "1")
    router = _fleet(tiny, 2)
    for rid, rep in router.replicas.items():
        assert rep.se.page_audit is not None, rid
    router.submit([81, 3, 5, 7], 3, req_id="pa-0")
    router.run()
    # Seed a lifetime violation in replica 1's auditor ONLY: a decref
    # of a page whose shadow count is already zero (a double-free).
    router.replicas["1"].se.page_audit.record({"op": "decref", "page": 0})
    reports = router.page_audit_reports()
    assert sorted(reports) == ["0", "1"]
    assert reports["0"].op == "replica0" and reports["0"].ok
    bad = reports["1"]
    assert bad.op == "replica1" and not bad.ok
    assert any(v.kind == "double-free" for v in bad.violations)


def test_flight_dumps_carry_replica_id(tmp_path):
    from triton_distributed_tpu.obs.flight import (
        FlightRecorder, find_dumps, load_dump, validate_dump,
    )
    from triton_distributed_tpu.obs.postmortem import render

    fr = FlightRecorder(capacity=4, run_dir=str(tmp_path),
                        replica_id="3")
    fr.record({"iter": 0, "decoded": 1})
    path = fr.dump("evacuation", "unit test", 0)
    assert os.path.basename(path).startswith("replica3-flight-")
    data = load_dump(path)
    assert data["replica"] == "3"
    assert validate_dump(data, path=path) == []
    assert find_dumps(str(tmp_path)) == [path]
    assert "replica: 3" in render(data, path)
    # Un-namespaced recorders keep the legacy stem and stay findable.
    fr2 = FlightRecorder(capacity=4, run_dir=str(tmp_path))
    p2 = fr2.dump("evacuation", "unit test", 0)
    assert os.path.basename(p2).startswith("flight-")
    assert set(find_dumps(str(tmp_path))) == {path, p2}


def test_run_raises_instead_of_hanging(tiny):
    router = _fleet(tiny, 1)
    router.submit([5, 4, 3], 4, req_id="h-0")
    with pytest.raises(RuntimeError, match="never a hang"):
        router.run(max_iters=1)
