"""Prefix-reuse subsystem (ISSUE 15, docs/serving.md "Prefix cache").

The load-bearing contract: warm serve (a request whose prompt prefix is
resident in the radix-indexed page pool) must be TOKEN-IDENTICAL to
cold serve on both the xla and megakernel backends — including a
preempt/resume of a sharing request and a copy-on-write whose divergent
suffix crosses a page boundary — while refcounted pages are counted
once in the pool accounting, preempting a sharer never frees or
corrupts a page another reader holds, and cold cached chains evict in
refcount×recency order under pool pressure.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.models.config import ModelConfig, tiny_config
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.kv_cache import (
    PageAllocator, PageRefError,
)
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving.loop import ServingEngine
from triton_distributed_tpu.serving.prefix import (
    PrefixCache, PrefixConfigError,
)


@pytest.fixture(scope="module")
def ctx1():
    return initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])


@pytest.fixture(scope="module")
def tiny(ctx1):
    cfg = tiny_config()
    params = init_dense_llm(jax.random.key(0), cfg)
    return cfg, params


def _golden(engine, prompt, gen):
    return np.asarray(
        engine.serve(jnp.asarray([prompt], jnp.int32), gen_len=gen)
    )[0].tolist()


# ---------------------------------------------------------------------------
# PageAllocator refcounts — share = +ref, free = −ref, physical at zero.
# ---------------------------------------------------------------------------

def test_share_and_free_refcounted():
    al = PageAllocator(8, 8)
    got = al.alloc_pages("a", 2)
    assert got == [0, 1] and al.ref_count(0) == 1
    al.share_pages("b", got)
    assert al.ref_count(0) == 2 and al.pages("b") == [0, 1]
    assert al.free_count == 6
    # Freeing one sharer releases references, not bytes.
    al.free_pages("a")
    assert al.ref_count(0) == 1 and al.free_count == 6
    al.free_pages("b")
    assert al.ref_count(0) == 0 and al.free_count == 8


def test_named_ref_errors():
    al = PageAllocator(4, 4)
    with pytest.raises(PageRefError, match="share of page"):
        al.share_pages("x", [2])        # free page: nothing to share
    with pytest.raises(PageRefError, match="incref of page"):
        al.incref(1)
    al.alloc_pages("a", 1)
    al.free_pages("a")
    with pytest.raises(PageRefError, match="reference count is already"):
        al.decref(0)
    with pytest.raises(PageRefError, match="COW of page"):
        al.cow_page("a", 0)             # owner holds nothing


def test_cow_page_replaces_in_place():
    al = PageAllocator(8, 8)
    pages = al.alloc_pages("a", 3)
    al.share_pages("b", [pages[1]])
    new = al.cow_page("b", pages[1])
    assert new is not None and new != pages[1]
    assert al.pages("b") == [new]              # same position, private
    assert al.ref_count(pages[1]) == 1         # a's reference survives
    assert al.ref_count(new) == 1


def test_free_tail_respects_sharers():
    al = PageAllocator(8, 8)
    pages = al.alloc_pages("a", 3)
    al.share_pages("b", [pages[2]])
    assert al.free_tail("a", 1) == 2           # released 2 references
    # Page 2 had a second reader: it must NOT have rejoined the pool.
    assert al.ref_count(pages[2]) == 1
    assert al.free_count == 8 - 2              # pages 1 freed, 0+2 held


# ---------------------------------------------------------------------------
# PrefixCache — radix index, partial-tail match, eviction order.
# ---------------------------------------------------------------------------

def test_prefix_config_error():
    with pytest.raises(PrefixConfigError, match="page_size"):
        PrefixCache(PageAllocator(4, 4), 0)


def test_match_full_and_partial_with_cap():
    al = PageAllocator(16, 16)
    cache = PrefixCache(al, 4)
    toks = list(range(30, 46))                  # 16 tokens = 4 pages
    pages = al.alloc_pages("a", 4)
    assert cache.insert(toks, pages) == 4
    # Identical prompt: cap at len-1 → 3 full pages + 3-token partial.
    hit, full, partial = cache.match(toks)
    assert hit == 15 and full == pages[:3] and partial == pages[3]
    # Divergence INSIDE page 2: LCP partial match.
    q = toks[:9] + [99, 98, 97, 96]
    hit, full, partial = cache.match(q)
    assert hit == 9 and full == pages[:2] and partial == pages[2]
    # No overlap at all.
    hit, full, partial = cache.match([1, 2, 3, 4, 5])
    assert (hit, full, partial) == (0, [], None)
    # match is a READ-ONLY probe: stats move only on commit_match (the
    # committed admission), so a pool-short retry can't inflate them.
    assert cache.hits == 0 and cache.lookups == 0
    cache.commit_match(toks, 15)
    cache.commit_match([1, 2, 3, 4, 5], 0)
    assert cache.hits == 1 and cache.lookups == 2
    assert cache.tokens_saved == 15


def test_eviction_refcount_times_recency():
    al = PageAllocator(8, 8)
    cache = PrefixCache(al, 2)
    a = al.alloc_pages("a", 2)
    b = al.alloc_pages("b", 2)
    cache.insert([1, 2, 3, 4], a)              # chain A (older)
    cache.insert([5, 6, 7, 8], b)              # chain B (newer)
    al.free_pages("b")                          # B pages now cache-only
    hit_b = cache.match([5, 6, 7, 8, 9])[0]
    cache.commit_match([5, 6, 7, 8, 9], hit_b)  # ...but recently used
    al.free_pages("a")                          # A cache-only, colder
    # Chain A's pages still carry a live sharer? No — both are
    # cache-only; A is colder, so A's LEAF evicts first.
    freed = cache.reclaim(1)
    assert freed == 1
    assert a[1] not in cache._pages and a[0] in cache._pages
    # A page with a live reader is never evictable, however cold.
    al.share_pages("c", [a[0]])
    assert cache.reclaim(10) == 2               # b's two leaves... a[0] kept
    assert a[0] in cache._pages and not any(
        p in cache._pages for p in (a[1], b[0], b[1]))


def test_reclaim_via_alloc_pages_hook():
    al = PageAllocator(4, 4)
    cache = PrefixCache(al, 2)
    pages = al.alloc_pages("a", 4)
    cache.insert([1, 2, 3, 4, 5, 6, 7, 8], pages)
    al.free_pages("a")                          # all 4 pages cache-held
    assert al.free_count == 0 and al.reclaimable() == 4
    # A fresh allocation evicts cold chains instead of failing.
    got = al.alloc_pages("b", 2)
    assert got is not None and len(got) == 2
    assert cache.evictions >= 2


def test_invalidate_releases_everything():
    al = PageAllocator(4, 4)
    cache = PrefixCache(al, 2)
    pages = al.alloc_pages("a", 2)
    cache.insert([1, 2, 3, 4], pages)
    al.free_pages("a")
    assert cache.invalidate() == 2
    assert al.free_count == 4 and cache.pages_held == 0
    assert cache.match([1, 2, 3, 4, 5]) == (0, [], None)


# ---------------------------------------------------------------------------
# Warm serve — token parity vs cold, xla backend.
# ---------------------------------------------------------------------------

def test_warm_serve_parity_and_cow_across_page_boundary(ctx1, tiny):
    """The acceptance shape: request D indexes a 4-full-page chain;
    request F shares 3 full pages + a partial page (divergence INSIDE
    page 3) so its suffix write COWs the boundary page AND continues
    into the next page — token-identical to cold serve throughout."""
    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    se = ServingEngine(engine, max_batch=2, num_pages=16,
                       prefill_chunk=4, prefix_cache=True)
    pre = list(range(10, 22))
    pD = pre + [3, 5, 8, 9]                     # 16 tokens: 4 full pages
    pF = pre + [3, 5, 8, 30, 31, 32]            # diverges inside page 3
    gD = _golden(engine, pD, 5)
    gF = _golden(engine, pF, 6)
    rD, _ = se.submit(pD, 5, req_id="D")
    se.run()
    assert rD.tokens == gD and rD.prefix_hit_tokens_total == 0
    rF, _ = se.submit(pF, 6, req_id="F")
    se.run()
    assert rF.tokens == gF
    assert rF.prefix_hit_tokens_total == 15     # 12 full + 3 partial
    # Identical full prompt warm: cap at len-1.
    rD2, _ = se.submit(pD, 5, req_id="D2")
    se.run()
    assert rD2.tokens == gD and rD2.prefix_hit_tokens_total == 15
    # Pool accounting exact: refcounted pages counted once.
    al = se.sched.allocator
    assert al.free_count + se.prefix.pages_held == al.usable_pages
    assert se.prefix.pages_shared_peak > 0


def test_preempt_resume_of_sharer_with_parity(ctx1, tiny):
    """Preempting a sharer mid-decode releases only ITS references;
    the survivor keeps decoding off the shared pages and the preempted
    request resumes (warm, off the surviving chain) with parity."""
    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    se = ServingEngine(engine, max_batch=2, num_pages=12,
                       prefill_chunk=4, prefix_cache=True)
    pre = list(range(40, 52))
    pA = pre + [3, 5]
    pB = pre + [7, 9]
    gA = _golden(engine, pA, 8)
    gB = _golden(engine, pB, 8)
    r0, _ = se.submit(pA, 8, req_id="s-0", priority=1)
    se.run()
    rA, _ = se.submit(pB, 8, req_id="s-A", priority=1)
    rB, _ = se.submit(pA, 8, req_id="s-B", priority=0)
    for _ in range(5):
        se.step()
    assert rA.prefix_hit_tokens_total > 0
    assert rB.prefix_hit_tokens_total > 0
    shared_before = {p: np.asarray(se._cache.k_pools)[:, p].copy()
                     for p in sorted(se.prefix._pages)}
    se.sched._preempt(rB)                      # evict the sharer
    pools = np.asarray(se._cache.k_pools)
    for p, before in shared_before.items():
        assert np.array_equal(pools[:, p], before)
    se.run()
    assert r0.tokens == gA and rA.tokens == gB and rB.tokens == gA


def test_decode_time_cow_copies_the_page(ctx1, tiny):
    """The general COW guard: an append target still carrying another
    reader is replaced by a private byte-copy before the launch."""
    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    se = ServingEngine(engine, max_batch=1, num_pages=8,
                       prefill_chunk=4, prefix_cache=True)
    r, _ = se.submit(list(range(60, 66)), 8, req_id="cow-0")
    for _ in range(4):
        se.step()
    assert r.state.name == "RUNNING"
    al = se.sched.allocator
    pages = al.pages(r.req_id)
    target = pages[r.kv_len // se.page]
    se.prefix.pin(target)                      # simulate a second reader
    assert al.ref_count(target) == 2
    golden = _golden(engine, list(range(60, 66)), 8)
    se.run()
    # The request never wrote the pinned page: it was COW'd away.
    assert al.pages(r.req_id) == []            # finished, refs released
    assert al.ref_count(target) == 1           # the pin's ref survives
    assert r.tokens == golden
    se.prefix.unpin(target)


def test_partial_pin_precedes_suffix_alloc():
    """The partially-matched page must be pinned BEFORE the suffix
    allocation: ``alloc_pages``' reclaim hook may otherwise evict (and
    physically free) a cold, cache-only partial page between the match
    and the pin — pinning a freed page is a PageRefError that would
    kill the serving loop on a routine warm admission."""
    from triton_distributed_tpu.serving.request import Request
    from triton_distributed_tpu.serving.scheduler import Scheduler

    al = PageAllocator(6, 6)
    cache = PrefixCache(al, 4)
    sched = Scheduler(num_slots=2, allocator=al, page_size=4,
                      capacity_tokens=24, max_waiting=4, prefix=cache)
    toks = list(range(10, 18))            # 8 tokens: 2 chunks
    pages = al.alloc_pages("seed", 2)
    cache.insert(toks, pages)
    al.free_pages("seed")                 # chain is cache-only (evictable)
    partial_page = pages[1]
    seen = {}
    real_alloc = al.alloc_pages

    def spy(owner, n=1):
        seen["ref_at_alloc"] = al.ref_count(partial_page)
        return real_alloc(owner, n)

    al.alloc_pages = spy
    req = Request(prompt=toks[:6] + [99, 98, 97], max_new_tokens=2)
    sched.admit(req, 0.0)
    admitted = sched.schedule_admissions()
    assert [r.req_id for r in admitted] == [req.req_id]
    # Cache ref + the admission's read-pin, already held when the
    # suffix allocation (and so any reclaim it triggers) ran.
    assert seen["ref_at_alloc"] == 2
    assert req._prefix_partial == partial_page
    assert al.ref_count(partial_page) == 2


def test_admission_undo_when_pool_short(ctx1, tiny):
    """A warm admission whose fresh-suffix reservation fails must undo
    its shares and stay queued whole — no leaked references."""
    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    se = ServingEngine(engine, max_batch=2, num_pages=6,
                       prefill_chunk=4, prefix_cache=True)
    pre = list(range(70, 82))
    r0, _ = se.submit(pre + [1, 2], 10, req_id="u-0")
    se.run()
    # Occupy the pool with a long-running request so the warm
    # follow-up's suffix cannot reserve.
    r1, _ = se.submit(list(range(1, 13)), 10, req_id="u-1", priority=1)
    for _ in range(6):
        se.step()
    refs_before = {p: se.sched.allocator.ref_count(p)
                   for p in sorted(se.prefix._pages)}
    r2, _ = se.submit(pre + [9, 8], 4, req_id="u-2", priority=0)
    se.step()
    if r2.state.name == "WAITING":
        # No reference may have been ADDED by the failed admission
        # (reclaim may legitimately have evicted cold cache-only pages
        # between the snapshots — fewer refs is fine, more is a leak).
        refs_after = {p: se.sched.allocator.ref_count(p)
                      for p in sorted(se.prefix._pages)}
        assert all(refs_after[p] <= refs_before.get(p, 1)
                   for p in refs_after)
        assert se.sched.allocator.pages("u-2") == []
    se.run()


# ---------------------------------------------------------------------------
# Megakernel backend — warm parity + COW on the paged workspace.
# ---------------------------------------------------------------------------

def test_megakernel_warm_serve_parity_with_cow():
    """Warm serve on the persistent paged workspace: the second
    request's prefix (incl. an in-page divergence COW whose suffix
    crosses into the next TILE page) reads resident pool tiles and
    stays token-identical to cold xla serve."""
    cfg = ModelConfig(hidden_size=256, intermediate_size=256,
                      num_layers=2, num_heads=2, num_kv_heads=1,
                      head_dim=128, vocab_size=512, qk_norm=True,
                      dtype="float32")
    params = init_dense_llm(jax.random.PRNGKey(1), cfg)
    ctx = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                 devices=jax.devices()[:1])
    rng = np.random.default_rng(9)
    base = rng.integers(0, 512, 264).tolist()   # 2 full pages + partial
    pB = base[:250] + rng.integers(0, 512, 12).tolist()
    oracle = Engine(cfg, params, ctx, backend="xla", max_seq=384)
    gA = _golden(oracle, base, 4)
    gB = _golden(oracle, pB, 4)
    eng = Engine(cfg, params, ctx, backend="megakernel", max_seq=384,
                 page_size=128)
    se = ServingEngine(eng, max_batch=2, num_pages=8, prefill_chunk=128,
                       prefix_cache=True)
    rA, _ = se.submit(base, 4, req_id="mk-A")
    se.run()
    assert se._mk is not None, "lane demoted"
    assert rA.tokens == gA
    rB, _ = se.submit(pB, 4, req_id="mk-B")
    se.run()
    assert se._mk is not None and eng.backend == "megakernel"
    assert rB.tokens == gB
    assert rB.prefix_hit_tokens_total == 250    # 128 full + 122 partial


# ---------------------------------------------------------------------------
# Observability — series published, report contract.
# ---------------------------------------------------------------------------

def test_prefix_metrics_and_report_gate(ctx1, tiny, tmp_path):
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import report as obs_report

    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    run_dir = str(tmp_path / "prefix-run")
    obs.start_run(run_dir)
    try:
        se = ServingEngine(engine, max_batch=2, num_pages=16,
                           prefill_chunk=4, prefix_cache=True)
        pre = list(range(20, 32))
        se.submit(pre + [1, 2], 4, req_id="g-0")
        se.run()
        se.submit(pre + [5, 6], 4, req_id="g-1")
        se.run()
        snap = obs_metrics.registry().snapshot()
    finally:
        obs.finish_run()
    assert obs_metrics.PREFIX_HIT_RATE in snap
    assert obs_metrics.PREFIX_PAGES_SHARED in snap
    assert snap[obs_metrics.PREFIX_TOKENS_SAVED]["value"] > 0
    assert snap[obs_metrics.PREFIX_HIT_RATE]["value"] > 0
    rc = obs_report.main([run_dir, "--check"])
    assert rc == 0


def test_request_records_carry_prefix_hits(ctx1, tiny):
    from triton_distributed_tpu.serving.loadgen import request_records

    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    se = ServingEngine(engine, max_batch=2, num_pages=16,
                       prefill_chunk=4, prefix_cache=True)
    pre = list(range(33, 45))
    r0, _ = se.submit(pre + [1], 3, req_id="rr-0")
    se.run()
    r1, _ = se.submit(pre + [2], 3, req_id="rr-1")
    se.run()
    recs = {r["req_id"]: r for r in request_records([r0, r1])}
    assert recs["rr-0"]["prefix_hit_tokens"] == 0
    assert recs["rr-1"]["prefix_hit_tokens"] > 0


def test_shared_prefix_loadspec_deterministic():
    from triton_distributed_tpu.serving.loadgen import (
        LoadSpec, build_trace,
    )

    spec = LoadSpec(n_requests=6, seed=0, prefix_families=2,
                    prefix_len=12)
    t1 = build_trace(spec)
    t2 = build_trace(spec)
    assert t1 == t2
    fams = {}
    for item in t1:
        fams.setdefault(item["family"], set()).add(
            tuple(item["prompt"][:12]))
    # One preamble per family, shared across its requests.
    assert all(len(v) == 1 for v in fams.values()) and len(fams) == 2
    # A different trace seed keeps the SAME preambles (warm-rung shape).
    t3 = build_trace(LoadSpec(n_requests=6, seed=7, prefix_families=2,
                              prefix_len=12))
    assert t3[0]["prompt"][:12] == t1[0]["prompt"][:12]
