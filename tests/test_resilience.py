"""Resilience subsystem tests (ISSUE 6): the fault matrix per class on
allgather / allreduce / p2p / one fused op, deadline-bounded waits, and
the Engine demotion ladder (degraded xla path token-identical).

The fault-matrix cases run in the comm-lint replay lane (CPU, no
hardware): a seeded FaultPlan overlays the tracer's patch-point shims and
the chaos harness classifies the outcome — so the coverage here is the
same machinery `python -m triton_distributed_tpu.resilience.chaos` gates
in CI, pinned per (op, fault class).
"""

import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.resilience import (
    CommTimeoutError,
    FaultClass,
    FaultInjectionError,
    FaultPlan,
    deadline,
    is_transient,
)
from triton_distributed_tpu.resilience import chaos

MATRIX_TEST_OPS = ("allgather", "allreduce", "p2p", "allgather_gemm")


_BASELINES: dict = {}


def _case(op: str, fault: FaultClass, seed: int = 0) -> chaos.CaseResult:
    from triton_distributed_tpu.analysis.registry import build_registry

    driver = build_registry((2,))[op]
    axes, dims = ("tp",), (2,)
    if op not in _BASELINES:   # one clean replay per op, shared by cases
        _BASELINES[op] = chaos._clean_baseline(driver, axes, dims,
                                               f"{op}@2")
    return chaos.run_case(op, axes, dims, fault, seed=seed,
                          baseline_hashes=_BASELINES[op], driver=driver)


@pytest.mark.parametrize("fault", list(FaultClass),
                         ids=[f.value for f in FaultClass])
@pytest.mark.parametrize("op", MATRIX_TEST_OPS)
def test_fault_matrix_case(op, fault):
    """Every (op, fault-class) case lands on its expected verdict with a
    fired-fault record; detections carry a named diagnostic."""
    case = _case(op, fault)
    assert case.ok, (case.verdict, case.expected, case.diagnostics)
    assert case.n_fired >= 1
    if case.verdict == "detected":
        assert case.detected_by in ("commlint", "parity", "error")
        assert case.diagnostics, "detection must carry a named diagnostic"


def test_drop_fault_names_the_semaphore():
    case = _case("allgather", FaultClass.DROP_SIGNAL)
    assert case.verdict == "detected" and case.detected_by == "commlint"
    text = "\n".join(case.diagnostics)
    # The diagnostic names the starved semaphore and the wedged rank.
    assert "sem" in text and "rank 1" in text


def test_fault_cases_are_deterministic():
    a = _case("allreduce", FaultClass.DUP_SIGNAL, seed=3)
    b = _case("allreduce", FaultClass.DUP_SIGNAL, seed=3)
    assert a.diagnostics == b.diagnostics
    assert (a.verdict, a.n_violations) == (b.verdict, b.n_violations)


def test_instrument_overlay_stacks_and_unwinds():
    from triton_distributed_tpu.language import instrument

    assert instrument.active_layers() == 0
    base = instrument.originals(["rank"])["rank"]
    instrument.install({"rank": lambda axis="tp": 7})
    try:
        with pytest.raises(instrument.InstrumentationError):
            instrument.install({"rank": lambda axis="tp": 8})  # no overlay
        instrument.install({"rank": lambda axis="tp": 8}, overlay=True)
        from triton_distributed_tpu.language import distributed_ops

        assert distributed_ops.rank() == 8
        instrument.uninstall()
        assert distributed_ops.rank() == 7
    finally:
        while instrument.active_layers():
            instrument.uninstall()
    from triton_distributed_tpu.language import distributed_ops

    assert distributed_ops.rank is base


# ---------------------------------------------------------------------------
# Deadline-bounded waits.
# ---------------------------------------------------------------------------

def test_deadline_converts_hang_to_named_error():
    sem = chaos._FakeInterpretSemaphore("tests/sem0")
    deadline.drain_timeout_events()
    with pytest.raises(CommTimeoutError) as ei:
        deadline.semaphore_wait_with_deadline(sem, 3, 1, timeout_s=0.05,
                                              nap_s=0.002)
    err = ei.value
    assert (err.sem, err.rank, err.expected, err.observed) == \
        ("tests/sem0", 1, 3, 0)
    msg = str(err)
    assert "tests/sem0" in msg and "expected delta 3" in msg
    events = deadline.drain_timeout_events()
    assert len(events) == 1 and events[0].kind == "timeout"
    assert events[0].sem == "tests/sem0" and events[0].amount == 3


def test_deadline_signalled_wait_completes_and_consumes():
    sem = chaos._FakeInterpretSemaphore()
    threading.Timer(0.01, sem.signal, args=(0, 2)).start()
    deadline.semaphore_wait_with_deadline(sem, 2, 0, timeout_s=5.0,
                                          nap_s=0.002)
    assert sem.count_by_core[0] == 0  # consumed
    assert deadline.drain_timeout_events() == []


def test_deadline_progress_resets_budget():
    """A slow-but-live producer never trips the deadline: each increment
    resets the progress budget even though the total wait exceeds it."""
    sem = chaos._FakeInterpretSemaphore()
    for i in range(4):
        threading.Timer(0.01 * (i + 1), sem.signal, args=(0, 1)).start()
    deadline.semaphore_wait_with_deadline(sem, 4, 0, timeout_s=0.03,
                                          nap_s=0.002)
    assert deadline.drain_timeout_events() == []


def test_wait_budget_env_config(monkeypatch):
    monkeypatch.setenv("TDTPU_WAIT_TIMEOUT_MS", "1500")
    monkeypatch.setenv("TDTPU_WAIT_NAP_MS", "2")
    assert deadline.wait_timeout_s() == pytest.approx(1.5)
    assert deadline.wait_nap_s() == pytest.approx(0.002)
    monkeypatch.setenv("TDTPU_WAIT_TIMEOUT_MS", "0")  # escape hatch
    assert deadline.wait_timeout_s() == 0.0
    monkeypatch.delenv("TDTPU_WAIT_TIMEOUT_MS")
    assert deadline.wait_timeout_s() == pytest.approx(
        deadline.DEFAULT_TIMEOUT_MS / 1e3)


def test_wait_budget_context_config(ctx, monkeypatch):
    from triton_distributed_tpu.runtime import context as ctx_mod

    monkeypatch.delenv("TDTPU_WAIT_TIMEOUT_MS", raising=False)
    ctx_mod.set_context(dataclasses.replace(ctx, wait_timeout_ms=250.0))
    try:
        assert deadline.wait_timeout_s() == pytest.approx(0.25)
        # Env wins over the context field.
        monkeypatch.setenv("TDTPU_WAIT_TIMEOUT_MS", "100")
        assert deadline.wait_timeout_s() == pytest.approx(0.1)
    finally:
        ctx_mod.set_context(ctx)


def test_wait_and_consume_token_accept_timeout():
    from triton_distributed_tpu.language import distributed_ops as dl

    assert dl.consume_token(5, 0, timeout_ns=10_000) == 5
    # wait's timeout_ns is declarative (no TPU lowering) — the signature
    # must accept it through the replay shim as well.
    from triton_distributed_tpu.analysis.tracer import trace_op

    def driver(d):
        from triton_distributed_tpu.language import wait as pkg_wait
        from triton_distributed_tpu.analysis.tracer import FakeSem

        pkg_wait(FakeSem("t/sem"), 1, timeout_ns=1_000_000)

    ts = trace_op(driver, ("tp",), (1,))
    assert any(e.kind == "wait" for e in ts.events[0])


# ---------------------------------------------------------------------------
# Straggler rotation (shared resolver + fused-op acceptance).
# ---------------------------------------------------------------------------

def test_resolve_straggler_forms():
    from triton_distributed_tpu.language.distributed_ops import (
        resolve_straggler,
    )

    assert resolve_straggler(None, 4, 2) is None
    assert resolve_straggler((1, 64), 4, 2) == (1, 64)
    rank, cycles = resolve_straggler(("rotate", 64), 4, 6)
    assert int(rank) == 2 and cycles == 64
    rank, _ = resolve_straggler(("rotate", 64), 4, None)
    assert int(rank) == 0


def test_fused_ops_accept_rotating_straggler():
    """allgather_gemm / gemm_reduce_scatter take ("rotate", cycles): the
    straggle lands on rank (call_index % n) — verified in the replay lane
    (uniform fault coverage with the stream collectives)."""
    from triton_distributed_tpu.analysis.tracer import trace_op
    from triton_distributed_tpu.ops.allgather_gemm import (
        AGGemmConfig, ag_gemm_local,
    )
    from triton_distributed_tpu.ops.gemm_reduce_scatter import (
        GemmRSConfig, gemm_rs_local,
    )

    def _arr(*shape):
        n = int(np.prod(shape))
        return (np.arange(n, dtype=np.float32).reshape(shape) % 7)

    def driver(d):
        n = d["tp"]
        ag_gemm_local(_arr(16, 128), _arr(128, 128), axis="tp",
                      num_ranks=n,
                      cfg=AGGemmConfig(straggler=("rotate", 64),
                                       call_index=1))
        gemm_rs_local(_arr(n * 16, 128), _arr(128, 128), axis="tp",
                      num_ranks=n,
                      cfg=GemmRSConfig(straggler=("rotate", 64),
                                       call_index=1))

    ts = trace_op(driver, ("tp",), (2,), name="fused_rotate")
    straggles = {r: [e for e in evs if e.kind == "straggle"]
                 for r, evs in enumerate(ts.events)}
    assert len(straggles[1]) == 2   # call_index 1 % 2 == rank 1, both ops
    assert straggles[0] == []


# ---------------------------------------------------------------------------
# Engine degradation ladder.
# ---------------------------------------------------------------------------

def _tiny_engine_setup():
    from triton_distributed_tpu.models import init_dense_llm, tiny_config

    cfg = tiny_config()
    params = init_dense_llm(jax.random.key(0), cfg)
    ids = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab_size)
    return cfg, params, ids


def _fresh_registry():
    from triton_distributed_tpu.obs import metrics as obs_metrics

    return obs_metrics.set_registry(obs_metrics.Registry())


def test_engine_demotes_to_xla_with_token_parity(ctx):
    """Acceptance: a persistent injected fault on the fused path demotes
    to xla within the retry budget, and the degraded output is
    token-identical to the healthy golden run."""
    from triton_distributed_tpu.models import Engine

    cfg, params, ids = _tiny_engine_setup()
    reg = _fresh_registry()
    golden = Engine(cfg, params, ctx, backend="xla", max_seq=32
                    ).serve(ids, 4)

    eng = Engine(cfg, params, ctx, backend="overlap", max_seq=32)
    assert eng._ladder == ["overlap", "xla"]
    # Persistent crash on the fused path's comm kernels (the AR family the
    # overlap backend routes reductions through at this shape); the golden
    # xla rung launches none of them.
    plan = FaultPlan(FaultClass.CRASH, persistent=True, match="_ar_")
    with plan.active(), pytest.warns(RuntimeWarning, match="demoted"):
        out = eng.serve(ids, 4)

    assert eng.backend == "xla" and eng._rung == 1
    np.testing.assert_array_equal(np.asarray(out), np.asarray(golden))
    assert reg.get("tdtpu_engine_demotions_total").value == 1
    assert reg.get("tdtpu_engine_step_retries_total").value >= 1
    assert reg.get("tdtpu_engine_backend_rung").value == 1
    assert plan.fired and plan.fired[0].cls == "crash"


def test_engine_clean_run_never_demotes(ctx):
    """Acceptance (no false positives): a clean serve keeps its backend
    and registers no demotion."""
    from triton_distributed_tpu.models import Engine

    cfg, params, ids = _tiny_engine_setup()
    reg = _fresh_registry()
    eng = Engine(cfg, params, ctx, backend="xla", max_seq=32)
    eng.serve(ids, 4)
    assert eng.backend == "xla" and eng._rung == 0
    assert reg.get("tdtpu_engine_demotions_total") is None


def test_engine_nontransient_error_propagates(ctx):
    """Programming errors are not degraded around: a bad argument raises
    through the ladder untouched."""
    from triton_distributed_tpu.models import Engine

    cfg, params, _ = _tiny_engine_setup()
    eng = Engine(cfg, params, ctx, backend="xla", max_seq=16)
    with pytest.raises(ValueError, match="exceeds max_seq"):
        eng.serve(jnp.zeros((1, 32), jnp.int32), 4)
    assert eng._rung == 0


def test_is_transient_classification():
    assert is_transient(FaultInjectionError("x"))
    assert is_transient(CommTimeoutError(sem="s", rank=0, expected=1,
                                         observed=0, waited_s=1.0,
                                         timeout_s=1.0))
    assert is_transient(RuntimeError("backend blew up"))
    assert not is_transient(ValueError("bad arg"))
    assert not is_transient(TypeError("bad type"))


def test_slo_streak_drives_demotion_and_repromotion(ctx, tmp_path,
                                                    monkeypatch):
    """A violation streak demotes (watchdog-driven), a clean streak
    probes re-promotion; the streak itself is published as a registry
    gauge (the satellite fix: the watchdog no longer only observes)."""
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.models import Engine
    from triton_distributed_tpu.obs import metrics as obs_metrics
    from triton_distributed_tpu.runtime import initialize_distributed
    from triton_distributed_tpu.runtime.context import set_context

    cfg, params, _ = _tiny_engine_setup()
    ids = jnp.zeros((1, 8), jnp.int32)
    ctx1 = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])
    try:
        eng = Engine(cfg, params, ctx1, backend="auto", max_seq=32)
        assert eng._ladder == ["auto", "xla"]
        monkeypatch.setenv("TDTPU_DEMOTE_AFTER", "2")
        monkeypatch.setenv("TDTPU_PROMOTE_AFTER", "1")
        monkeypatch.setenv("TDTPU_SLO_TOKENS_S_MIN", "1e15")  # unmeetable
        obs.start_run(str(tmp_path / "run"))
        try:
            eng.serve(ids, 3)
            reg = obs_metrics.registry()
            assert reg.get("tdtpu_slo_violation_streak").value == 1
            assert eng._rung == 0
            with pytest.warns(RuntimeWarning, match="demoted"):
                eng.serve(ids, 3)
            assert eng._rung == 1 and eng.backend == "xla"
            assert reg.get("tdtpu_engine_demotions_total").value == 1
            # Clean streak (floor removed) probes re-promotion.
            monkeypatch.delenv("TDTPU_SLO_TOKENS_S_MIN")
            with pytest.warns(RuntimeWarning, match="promoted"):
                eng.serve(ids, 3)
            assert eng._rung == 0 and eng.backend == "auto"
        finally:
            run_dir = obs.finish_run()
        # The degradation lane: the snapshot carries the demotion, and
        # report --check fails on it unless explicitly allowed.
        from triton_distributed_tpu.obs import report as obs_report

        metrics = obs_report.load_metrics(run_dir)
        assert obs_report.degradation_count(metrics) == 1
        rc_fail = obs_report.main([run_dir, "--check", "--require-series",
                                   "", "--allow-slo-violations"])
        assert rc_fail == 1
        rc_ok = obs_report.main([run_dir, "--check", "--require-series",
                                 "", "--allow-slo-violations",
                                 "--allow-degradation"])
        assert rc_ok == 0
    finally:
        set_context(ctx)


def test_chaos_json_report_shape(tmp_path):
    """The CLI's machine-readable report (CI artifact contract)."""
    rc = chaos.main(["--op", "allreduce", "--fault", "drop_signal",
                     "--ranks", "2",
                     "--json", str(tmp_path / "chaos.json")])
    assert rc == 0
    import json

    rep = json.loads((tmp_path / "chaos.json").read_text())
    assert rep["ok"] is True
    verdicts = {(c["op"], c["fault"]): c["verdict"] for c in rep["cases"]}
    assert verdicts[("allreduce", "drop_signal")] == "detected"
    assert verdicts[("deadline", "hang_no_producer")] == "detected"
