"""MoE AllToAll golden tests on the 8-device CPU mesh.

Reference test pattern: test/nvidia/test_all_to_all.py — correctness vs a
permutation-based golden (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops.all_to_all import (
    fast_all_to_all,
    dispatch_layout,
    combine_layout,
)


def _random_case(rng, n, epr, cap, hidden, dtype):
    """Random splits + send buffers honoring the layout contract."""
    splits = rng.integers(0, cap // n, size=(n, n, epr)).astype(np.int32)
    send = np.zeros((n, n, cap, hidden), dtype)
    for d in range(n):
        for p in range(n):
            rows = int(splits[d, p].sum())
            send[d, p, :rows] = rng.standard_normal((rows, hidden))
    return jnp.asarray(send), jnp.asarray(splits)


@pytest.mark.parametrize("dtype", [np.float32, jnp.float8_e4m3fn],
                         ids=["f32", "fp8"])
def test_fast_all_to_all_golden(ctx, dtype):
    """Value-exact transport for fp32 AND float8_e4m3fn payloads — the
    reference's headline A2A payload is fp8 (README.md:96-97); fp8 slots
    halve the wire bytes of bf16 (sublane tiling 32 → cap stays a
    multiple of 32)."""
    n, epr, cap, hidden = 8, 4, 64, 128
    rng = np.random.default_rng(0)
    send, splits = _random_case(rng, n, epr, cap, hidden, dtype)

    recv, rsplits = fast_all_to_all(send, splits, ctx)
    recv, rsplits = np.asarray(recv), np.asarray(rsplits)

    # Golden: recv[d, p] rows = send[p, d] rows; splits transpose likewise.
    np.testing.assert_array_equal(rsplits,
                                  np.swapaxes(np.asarray(splits), 0, 1))
    for d in range(n):
        for p in range(n):
            rows = int(rsplits[d, p].sum())
            np.testing.assert_allclose(
                recv[d, p, :rows], np.asarray(send)[p, d, :rows],
                rtol=0, atol=0,
                err_msg=f"recv[{d},{p}] != send[{p},{d}]")


def test_fast_all_to_all_zero_and_full_slots(ctx):
    """Degenerate splits: some peers receive nothing, one receives a full
    slot — exercises zero-trip DMA loops and cap-boundary blocks."""
    n, epr, cap, hidden = 8, 2, 32, 128
    rng = np.random.default_rng(1)
    splits = np.zeros((n, n, epr), np.int32)
    splits[:, 0, 0] = cap  # everyone sends a full slot to rank 0
    send = np.zeros((n, n, cap, hidden), np.float32)
    send[:, 0] = rng.standard_normal((n, cap, hidden))

    recv, rsplits = fast_all_to_all(jnp.asarray(send), jnp.asarray(splits), ctx)
    recv, rsplits = np.asarray(recv), np.asarray(rsplits)
    for p in range(n):
        np.testing.assert_allclose(recv[0, p], send[p, 0], rtol=0, atol=0)
    assert rsplits[1:].sum() == 0


def test_dispatch_combine_round_trip(ctx):
    """dispatch_layout → fast_all_to_all → combine_layout vs a pure-jax MoE
    dispatch golden (tokens grouped per destination expert)."""
    n, epr, hidden, m = 8, 4, 128, 48
    num_experts = n * epr
    cap = 64
    rng = np.random.default_rng(2)
    tokens = rng.standard_normal((n, m, hidden)).astype(np.float32)
    eids = rng.integers(0, num_experts, size=(n, m)).astype(np.int32)

    # Per-device layouts (host-side XLA, no mesh needed).
    layout = jax.vmap(
        lambda t, e: dispatch_layout(t, e, num_experts, n, cap))(
            jnp.asarray(tokens), jnp.asarray(eids))
    sbufs, ssplits = layout.send_buf, layout.send_splits

    recv, rsplits = fast_all_to_all(sbufs, ssplits, ctx)

    flat, leid, gsizes = jax.vmap(combine_layout)(recv, rsplits)
    flat, leid, gsizes = np.asarray(flat), np.asarray(leid), np.asarray(gsizes)

    # Golden: for every (device d, local expert j) the multiset of received
    # tokens equals the tokens routed to global expert d*epr+j anywhere.
    for d in range(n):
        for j in range(epr):
            ge = d * epr + j
            want = tokens[eids == ge]                      # (k, hidden)
            got = flat[d][leid[d] == j]
            assert got.shape == want.shape, (d, j, got.shape, want.shape)
            # Sort rows for multiset comparison (arrival order differs).
            order_w = np.lexsort(want.T)
            order_g = np.lexsort(got.T)
            np.testing.assert_allclose(got[order_g], want[order_w],
                                       rtol=0, atol=0)
    assert (gsizes.sum() == (np.asarray(eids) >= 0).sum())
    # Lossless cap -> overflow indicator reports zero drops everywhere.
    assert int(np.asarray(layout.overflow).sum()) == 0


def test_dispatch_layout_overflow_reported():
    """Undersized cap drops tokens — and says so (VERDICT r2 #9: the
    reference's MAX_M contract made checkable instead of silent)."""
    m, hidden, n, num_experts, cap = 16, 8, 2, 4, 4
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.standard_normal((m, hidden)), jnp.float32)
    eids = jnp.zeros((m,), jnp.int32)          # all to expert 0 => rank 0
    lay = dispatch_layout(tokens, eids, num_experts, n, cap)
    assert int(lay.overflow) == m - cap
    full = dispatch_layout(tokens, eids, num_experts, n, m)
    assert int(full.overflow) == 0


def test_a2a_stream_parity_repeated_calls(ctx):
    """Barrier-free parity AllToAll (VERDICT r2 #6): repeated calls over one
    persistent workspace with a rotating straggler; every round-trip exact.
    Data-dependent counts vary per call (the zero-block edge included)."""
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.ops.all_to_all import (
        a2a_stream_workspace, fast_all_to_all_stream,
    )
    from triton_distributed_tpu.runtime import shard_map_on

    n, cap, hidden, epr, steps = 8, 32, 128, 2, 60
    rng = np.random.default_rng(7)
    base = rng.standard_normal((n, n, cap, hidden)).astype(np.float32)
    # Per-step, per-destination row counts in [0, cap], incl. zeros.
    counts = rng.integers(0, cap + 1, size=(steps, n, n)).astype(np.int32)
    splits0 = counts[..., None] // epr
    splits1 = counts[..., None] - splits0
    splits = np.concatenate([splits0, splits1], axis=-1)  # (steps, n, n, epr)

    def run(sb, sp):
        sb, sp = sb[0], sp[0]        # (n, cap, h), (steps, n, epr)
        ws, idx = a2a_stream_workspace(n, cap, hidden, sb.dtype)

        def body(t, carry):
            ws, idx, err = carry
            x_t = sb * (1.0 + t)
            recv, rsp, ws, idx = fast_all_to_all_stream(
                x_t, sp[t], ws, idx, axis="tp", num_ranks=n,
                straggler=("rotate", 256))
            # Echo back: second stream call returns each rank's rows.
            back, _, ws, idx = fast_all_to_all_stream(
                recv, rsp, ws, idx, axis="tp", num_ranks=n)
            # Valid rows of slot p on the way back = what I originally sent p.
            rows = jnp.sum(sp[t], axis=1)             # (n,)
            mask = (jnp.arange(cap)[None, :, None] < rows[:, None, None])
            diff = jnp.abs(back - x_t) * mask
            return ws, idx, jnp.maximum(err, jnp.max(diff))

        _, idx, err = jax.lax.fori_loop(0, steps, body,
                                        (ws, idx, jnp.float32(0)))
        return err[None], idx[None]

    fn = shard_map_on(ctx, run, (P("tp"), P("tp")), (P("tp"), P("tp")))
    err, idx = fn(jnp.asarray(base), jnp.asarray(splits).transpose(1, 0, 2, 3))
    # Tolerance is 1-ulp scale only: XLA strength-reduces sb*(1+t) inside
    # the fori_loop, so the recomputed comparison tensor can differ from
    # the transported bytes by an ulp (a python-loop variant is bitwise
    # exact). Any real parity race shows up as O(1) stale-scale values.
    assert float(np.max(np.asarray(err))) < 1e-4, float(np.max(np.asarray(err)))
    assert int(np.asarray(idx)[0]) == 2 * steps


def test_fast_all_to_all_stream_fp8(ctx):
    """The barrier-free parity A2A carries float8_e4m3fn bit-exactly
    across repeated calls (fp8 decode payloads — the reference's 137us
    headline is fp8 hidden=7168)."""
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.ops.all_to_all import (
        a2a_stream_workspace, fast_all_to_all_stream,
    )
    from triton_distributed_tpu.runtime import shard_map_on

    n, epr, cap, hidden = 8, 2, 32, 64
    rng = np.random.default_rng(5)
    send, splits = _random_case(rng, n, epr, cap, hidden, jnp.float8_e4m3fn)

    def run(sb, sp):
        ws, idx = a2a_stream_workspace(n, cap, hidden, sb.dtype)
        outs = []
        for _ in range(3):
            rb, rs, ws, idx = fast_all_to_all_stream(
                sb[0], sp[0], ws, idx, num_ranks=n)
            outs.append(rb)
        return jnp.stack(outs)[None], rs[None]

    fn = shard_map_on(ctx, run, (P("tp"), P("tp")), (P("tp"), P("tp")))
    outs, rs = fn(send, splits)
    outs = np.asarray(outs.astype(jnp.float32))
    send_f = np.asarray(send.astype(jnp.float32))
    rs = np.asarray(rs)
    for t in range(3):
        for d in range(n):
            for p in range(n):
                rows = int(rs[d, p].sum())
                np.testing.assert_array_equal(
                    outs[d, t, p, :rows], send_f[p, d, :rows],
                    err_msg=f"call {t} recv[{d},{p}]")
