"""Request-scoped tracing + flight recorder (ISSUE 13,
docs/observability.md "Request tracing & postmortems").

The load-bearing contracts: the disabled hooks cost nothing on the
serving hot loop (< 20 µs/event), the TTFT decomposition PARTITIONS the
arrival → first-decode window (queue + prefill + migrate + decode ==
total, for a preempted-then-resumed AND a migrated request), flight
dumps are byte-deterministic under an injected clock, and
``obs.postmortem`` / ``obs.report --check`` gate the evidence.
"""

import json
import os
import time

import numpy as np
import pytest

import jax

from triton_distributed_tpu import obs
from triton_distributed_tpu.models.config import tiny_config
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.obs import flight as obs_flight
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.obs import postmortem as obs_postmortem
from triton_distributed_tpu.obs import reqtrace as obs_reqtrace
from triton_distributed_tpu.obs import report as obs_report
from triton_distributed_tpu.obs import trace as obs_trace
from triton_distributed_tpu.obs.reqtrace import ReqTracer
from triton_distributed_tpu.obs.slo import SLOConfig
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving.loadgen import (
    LoadSpec, build_trace, run_trace,
)
from triton_distributed_tpu.serving.loop import ServingEngine


@pytest.fixture(autouse=True)
def _no_leaked_run():
    """Every test starts and ends with tracer + reqtracer + step
    profiler disabled."""
    from triton_distributed_tpu.obs import stepprof as obs_stepprof

    obs_trace.disable()
    obs_reqtrace.disable()
    obs_stepprof.disable()
    yield
    obs_trace.disable()
    obs_reqtrace.disable()
    obs_stepprof.disable()


@pytest.fixture(scope="module")
def ctx1():
    return initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])


@pytest.fixture(scope="module")
def served(ctx1):
    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(7), cfg)
    return Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                  page_size=4)


class CounterClock:
    """Deterministic injectable clock: monotone, no wall time."""

    def __init__(self, step: float = 0.001):
        self.t = 0.0
        self.step = step

    def __call__(self) -> float:
        self.t += self.step
        return round(self.t, 6)


# ---------------------------------------------------------------------------
# Disabled-path overhead (the acceptance criterion's testable form).
# ---------------------------------------------------------------------------

def test_disabled_reqtrace_overhead_is_negligible():
    """The instrumented hook pattern — one global load, one None check —
    with no request tracer installed, for both event families the
    serving loop emits per iteration."""
    assert not obs_reqtrace.is_enabled()
    n = 50_000
    t0 = time.perf_counter()
    for _ in range(n):
        rt = obs_reqtrace.get_tracer()
        if rt is not None:
            rt.mark("r", "RUNNING", 0.0)
        rt = obs_reqtrace.get_tracer()
        if rt is not None:
            rt.span("r", "decode_step", 0.0, 1.0)
    per_event = (time.perf_counter() - t0) / (2 * n)
    assert per_event < 20e-6, \
        f"disabled reqtrace hook costs {per_event * 1e6:.2f} us"


# ---------------------------------------------------------------------------
# TTFT decomposition.
# ---------------------------------------------------------------------------

def test_decomposition_preempted_then_resumed_unit():
    """Hand-built lifecycle: preempted mid-prefill, re-admitted, first
    decode at t=9. Components must partition [arrival, window end]."""
    rt = ReqTracer()
    rt.arrival("r", 0.0)                    # WAITING
    rt.mark("r", "PREFILLING", 1.0)
    rt.mark("r", "PREEMPTED", 3.0)          # evicted mid-prefill
    rt.mark("r", "PREFILLING", 6.0)         # resumed (recompute)
    rt.mark("r", "RUNNING", 7.0)
    bd = rt.close_window("r", 9.0)
    assert bd["queue_ms"] == pytest.approx(4000.0)    # 0-1 and 3-6
    assert bd["prefill_ms"] == pytest.approx(3000.0)  # 1-3 and 6-7
    assert bd["migrate_ms"] == 0.0
    assert bd["decode_ms"] == pytest.approx(2000.0)   # 7-9
    assert bd["total_ms"] == pytest.approx(9000.0)
    # Idempotent: a second close returns the stored breakdown.
    assert rt.close_window("r", 99.0) is bd


def test_serving_decomposition_partitions_window(served, tmp_path):
    """A real traced serving run (page pressure forces a preemption):
    every request's components sum to its window, the preempted-then-
    resumed request included, and the four histogram series land in the
    registry with one observation per request."""
    obs.start_run(str(tmp_path))
    try:
        se = ServingEngine(served, max_batch=4, num_pages=8,
                           prefill_chunk=4, max_waiting=8,
                           clock=CounterClock())
        report = run_trace(se, build_trace(LoadSpec(
            n_requests=8, seed=0, mean_interarrival_iters=1.0)))
        reqs = report.pop("requests")
        recs = report["request_records"]
        snap = obs_metrics.registry().snapshot()
    finally:
        obs.finish_run()
    assert report["all_finished"]
    assert any(r.preemptions > 0 for r in reqs), \
        "pool sizing no longer exercises eviction"
    assert len(recs) == 8
    for rec in recs:
        bd = rec["ttft_breakdown_ms"]
        assert bd is not None, rec["req_id"]
        parts = sum(bd[k] for k in ("queue_ms", "prefill_ms",
                                    "migrate_ms", "decode_ms"))
        assert parts == pytest.approx(bd["total_ms"], abs=0.01), rec
    # A preempted request's queue component carries its re-admission
    # wait: it must exceed every never-preempted single-wait request's.
    preempted = [r for r in recs if r["preempted"]]
    assert preempted and all(r["migrated"] is False for r in recs)
    for series in obs_metrics.TTFT_COMPONENT_SERIES.values():
        assert snap[series]["count"] == 8, series


def test_migrated_request_decomposition(served):
    """Disagg tier: a migrated request spends real time MIGRATING — its
    migrate component is positive, the flags say so, and the partition
    invariant holds across the extra lifecycle stage."""
    from triton_distributed_tpu.disagg import (
        DisaggServingEngine, role_contexts,
    )

    pctx, dctx = role_contexts(jax.devices()[:2])
    pe = Engine(served.cfg, served.params, pctx, backend="xla",
                max_seq=64)
    de = Engine(served.cfg, served.params, dctx, backend="xla",
                max_seq=64, page_size=4)
    obs_reqtrace.enable()
    se = DisaggServingEngine(pe, de, max_batch=2, num_pages=8,
                             prefill_chunk=4, block_pages=1,
                             clock=CounterClock())
    trace = [{"req_id": "mig-0", "arrival_iter": 0,
              "prompt": list(range(30, 42)), "max_new_tokens": 4,
              "priority": 0}]
    report = run_trace(se, trace)
    report.pop("requests")
    rec = report["request_records"][0]
    assert se.disagg_active and rec["migrated"] and rec["state"] == \
        "FINISHED"
    bd = rec["ttft_breakdown_ms"]
    assert bd["migrate_ms"] > 0.0, \
        "a 3-block migration must spend time MIGRATING"
    parts = sum(bd[k] for k in ("queue_ms", "prefill_ms", "migrate_ms",
                                "decode_ms"))
    assert parts == pytest.approx(bd["total_ms"], abs=0.01)


# ---------------------------------------------------------------------------
# Flight recorder.
# ---------------------------------------------------------------------------

def _seeded_slo_run(flight_dir: str, eng) -> str:
    """One seeded serving run under an impossible SLO floor with a
    fully injected clock; returns the dump path it produced."""
    prior = obs_metrics.registry()
    obs_metrics.set_registry(obs_metrics.Registry())
    obs_reqtrace.enable()
    os.environ["TDTPU_FLIGHT_DIR"] = flight_dir
    try:
        se = ServingEngine(eng, max_batch=2, num_pages=8,
                           prefill_chunk=4,
                           slo_cfg=SLOConfig(tokens_per_s_min=1e12),
                           clock=CounterClock())
        rng = np.random.default_rng(5)
        for i in range(2):
            se.submit(rng.integers(0, 256, 7).tolist(), 3,
                      req_id=f"det-{i}")
        se.run()
        dumps = obs_flight.find_dumps(flight_dir)
        assert dumps, "impossible SLO floor produced no dump"
        return dumps[0]
    finally:
        os.environ.pop("TDTPU_FLIGHT_DIR", None)
        obs_reqtrace.disable()
        obs_metrics.set_registry(prior)


def test_flight_dump_deterministic_under_fixed_seed(served, tmp_path):
    p1 = _seeded_slo_run(str(tmp_path / "a"), served)
    p2 = _seeded_slo_run(str(tmp_path / "b"), served)
    with open(p1) as f1, open(p2) as f2:
        d1, d2 = json.load(f1), json.load(f2)
    assert os.path.basename(p1) == os.path.basename(p2)
    assert d1 == d2, "flight dump content is not deterministic"
    assert d1["trigger"]["kind"] == "slo_violation"
    assert d1["iterations"] and d1["requests"]


def test_postmortem_check_valid_and_malformed(served, tmp_path):
    dump = _seeded_slo_run(str(tmp_path), served)
    assert obs_postmortem.main([dump, "--check", "--quiet"]) == 0
    assert obs_postmortem.main([str(tmp_path), "--check",
                                "--quiet"]) == 0
    bad = tmp_path / "flight-9999-evacuation.json"
    bad.write_text(json.dumps({"schema": "nope"}))
    assert obs_postmortem.main([str(tmp_path), "--check",
                                "--quiet"]) == 1
    # obs.report --check gates the same malformed dump in a run dir.
    assert obs_report.main([str(tmp_path), "--check",
                            "--require-series", ""]) == 1
    bad.unlink()
    empty = tmp_path / "nodumps"
    empty.mkdir()
    assert obs_postmortem.main([str(empty), "--check", "--quiet"]) == 1


def test_flight_ring_is_bounded(tmp_path):
    rec = obs_flight.FlightRecorder(capacity=4, run_dir=str(tmp_path))
    for i in range(10):
        rec.record({"iter": i})
    path = rec.dump("slo_violation", "test", 10)
    data = obs_flight.load_dump(path)
    assert [r["iter"] for r in data["iterations"]] == [6, 7, 8, 9]
    assert not obs_flight.validate_dump(data)


# ---------------------------------------------------------------------------
# Report gating + utilization gauges.
# ---------------------------------------------------------------------------

def test_report_check_fails_on_missing_request_lane(tmp_path):
    """A serving-tier snapshot WITHOUT per-request timelines must fail
    --check (the postmortem evidence is gone); adding the lane — or the
    explicit opt-out — passes it. Since ISSUE 18 the step-phase lane
    (steps.spans.json) is gated the same way, and since ISSUE 19 the
    goodput lane (goodput.spans.json / timeline.json) too."""
    from triton_distributed_tpu.obs import stepprof as obs_stepprof

    reg = obs_metrics.Registry()
    reg.counter(obs_metrics.SERVE_FINISHED, "x").inc(3)
    reg.gauge(obs_metrics.KV_PAGES_RESIDENT, "x").set(8)
    reg.save(str(tmp_path))
    # The KV host-tier lane (ISSUE 20) gates the same way; opt out so
    # this test stays focused on the request/step/goodput lanes.
    args = [str(tmp_path), "--check", "--require-series", "",
            "--allow-missing-kv-tier"]
    assert obs_report.main(args) == 1
    assert obs_report.main(args + ["--allow-missing-request-lane",
                                   "--allow-missing-step-profile",
                                   "--allow-missing-goodput"]) == 0
    rt = ReqTracer()
    rt.arrival("req-lane", 0.0)
    rt.save(str(tmp_path / "requests.spans.json"))
    # Request lane restored — the other lanes still gate alone.
    assert obs_report.main(args) == 1
    assert obs_report.main(args + ["--allow-missing-step-profile",
                                   "--allow-missing-goodput"]) == 0
    sp = obs_stepprof.StepProfiler()
    sp.begin_iteration(0, 1.0)
    sp.finish_iteration(1.5)
    sp.save(str(tmp_path / "steps.spans.json"))
    assert obs_report.main(args + ["--allow-missing-goodput"]) == 0


def test_utilization_gauges_published(served, tmp_path):
    obs.start_run(str(tmp_path))
    try:
        se = ServingEngine(served, max_batch=2, num_pages=8,
                           prefill_chunk=4)
        se.submit(list(range(1, 8)), 2, req_id="gauge-0")
        while se.sched.has_work():
            se.step()
        snap = obs_metrics.registry().snapshot()
    finally:
        obs.finish_run()
    assert obs_metrics.SERVE_RUNNING_SLOTS in snap
    occ = snap[obs_metrics.KV_POOL_OCCUPANCY]["value"]
    assert 0.0 <= occ <= 1.0
    # The request lane landed in the run dir with one track per request.
    lane = json.load(open(tmp_path / "requests.spans.json"))
    names = [e["args"]["name"] for e in lane["traceEvents"]
             if e.get("name") == "thread_name"]
    assert names == ["gauge-0"]
    # And the merged report validates with the request lane present.
    assert obs_report.main([str(tmp_path), "--check",
                            "--require-series", ""]) == 0
