"""Model-level paged decode: must match the linear-cache decode exactly
when all sequences are at the same length, and support ragged lengths
(continuous batching) beyond what the linear path can express."""

import numpy as np

import jax
import jax.numpy as jnp

from triton_distributed_tpu.models.config import tiny_config
from triton_distributed_tpu.models.dense import (
    dense_decode_step, dense_decode_step_paged, dense_prefill, init_dense_llm,
)
from triton_distributed_tpu.models.kv_cache import (
    init_kv_cache, init_paged_model_cache,
)


def test_paged_decode_matches_linear(ctx):
    """Prefill with the linear cache, mirror it into pages, then decode one
    token both ways — logits must agree."""
    cfg = tiny_config()
    rng = np.random.default_rng(0)
    params = init_dense_llm(jax.random.PRNGKey(0), cfg)
    batch, seq, page, max_pages = 2, 6, 8, 4

    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                      jnp.int32)
    cache = init_kv_cache(cfg, batch, max_seq=16)
    logits, cache = dense_prefill(params, cfg, ids, cache)
    tok = jnp.argmax(logits, -1).astype(jnp.int32)

    # Mirror the linear cache into pages (identity tables).
    pcache = init_paged_model_cache(cfg, batch, page_size=page,
                                    max_pages=max_pages)
    kp = np.array(pcache.k_pools)
    vp = np.array(pcache.v_pools)
    table = np.asarray(pcache.page_table)
    kl = np.asarray(cache.k)   # (L, B, S_max, hkv, d)
    vl = np.asarray(cache.v)
    for li in range(cfg.num_layers):
        for b in range(batch):
            for t in range(seq):
                kp[li, table[b, t // page], t % page] = kl[li, b, t]
                vp[li, table[b, t // page], t % page] = vl[li, b, t]
    pcache = pcache._replace(
        k_pools=jnp.asarray(kp), v_pools=jnp.asarray(vp),
        kv_lens=jnp.full((batch,), seq, jnp.int32))

    lin_logits, _ = dense_decode_step(params, cfg, tok, cache)
    paged_logits, pcache2 = dense_decode_step_paged(params, cfg, tok, pcache)
    np.testing.assert_allclose(np.asarray(paged_logits),
                               np.asarray(lin_logits), rtol=2e-4, atol=2e-4)
    assert np.asarray(pcache2.kv_lens).tolist() == [seq + 1] * batch


def test_paged_decode_ragged_lengths(ctx):
    """Sequences at different lengths decode in ONE step (the linear cache
    cannot express this — its offset is global)."""
    cfg = tiny_config()
    rng = np.random.default_rng(1)
    params = init_dense_llm(jax.random.PRNGKey(1), cfg)
    batch, page, max_pages = 3, 8, 4
    lens = [5, 11, 0]

    pcache = init_paged_model_cache(cfg, batch, page_size=page,
                                    max_pages=max_pages)
    kp = np.array(pcache.k_pools)
    vp = np.array(pcache.v_pools)
    table = np.asarray(pcache.page_table)
    for li in range(cfg.num_layers):
        for b, n_tok in enumerate(lens):
            for t in range(n_tok):
                kp[li, table[b, t // page], t % page] = \
                    rng.standard_normal(kp.shape[-2:]) * 0.3
                vp[li, table[b, t // page], t % page] = \
                    rng.standard_normal(vp.shape[-2:]) * 0.3
    pcache = pcache._replace(
        k_pools=jnp.asarray(kp), v_pools=jnp.asarray(vp),
        kv_lens=jnp.asarray(lens, jnp.int32))

    tok = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch,)), jnp.int32)
    logits, pcache = dense_decode_step_paged(params, cfg, tok, pcache)
    assert np.isfinite(np.asarray(logits)).all()
    assert np.asarray(pcache.kv_lens).tolist() == [6, 12, 1]

    # Batch independence: sequence 1's logits must not depend on the other
    # sequences' cache contents (cross-contamination check).
    solo = init_paged_model_cache(cfg, 1, page_size=page,
                                  max_pages=max_pages)
    kp1 = np.array(solo.k_pools)
    vp1 = np.array(solo.v_pools)
    t1 = np.asarray(solo.page_table)
    for li in range(cfg.num_layers):
        for t in range(lens[1]):
            kp1[li, t1[0, t // page], t % page] = kp[li, table[1, t // page],
                                                     t % page]
            vp1[li, t1[0, t // page], t % page] = vp[li, table[1, t // page],
                                                     t % page]
    solo = solo._replace(k_pools=jnp.asarray(kp1), v_pools=jnp.asarray(vp1),
                         kv_lens=jnp.asarray([lens[1]], jnp.int32))
    solo_logits, _ = dense_decode_step_paged(params, cfg, tok[1:2], solo)
    np.testing.assert_allclose(np.asarray(solo_logits)[0],
                               np.asarray(logits)[1], rtol=2e-4, atol=2e-4)

def test_engine_paged_matches_linear_serve(ctx):
    """Engine(page_size=...) must generate IDENTICAL tokens to the linear
    engine — same params, same prompt, greedy decoding."""
    from triton_distributed_tpu.models.engine import Engine

    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(3), cfg)
    ids = jnp.asarray([[1, 2, 3, 4, 5]], jnp.int32)

    lin = Engine(cfg, params, ctx=ctx, backend="xla", max_seq=32)
    paged = Engine(cfg, params, ctx=ctx, backend="xla", max_seq=32,
                   page_size=8)
    out_lin = np.asarray(lin.serve(ids, gen_len=6))
    out_paged = np.asarray(paged.serve(ids, gen_len=6))
    np.testing.assert_array_equal(out_lin, out_paged)


def test_paged_saturation_flag(ctx):
    """PagedModelCache.saturated flags sequences at pool capacity, and
    dense_decode_step_paged holds their kv_lens at capacity instead of
    letting them run past the table (round-3 advisor: saturation used to be
    silent — serving loops can now evict)."""
    cfg = tiny_config()
    params = init_dense_llm(jax.random.PRNGKey(0), cfg)
    page, max_pages = 8, 2
    cache = init_paged_model_cache(cfg, 2, page_size=page,
                                   max_pages=max_pages)
    capacity = page * max_pages
    assert cache.capacity == capacity
    # Seq 0 one step short of capacity, seq 1 far from it.
    cache = cache._replace(
        kv_lens=jnp.asarray([capacity - 1, 4], jnp.int32))
    tok = jnp.zeros((2,), jnp.int32)
    for _ in range(3):
        _, cache = dense_decode_step_paged(params, cfg, tok, cache,
                                           num_ranks=1)
    sat = np.asarray(cache.saturated)
    assert sat.tolist() == [True, False]
    assert np.asarray(cache.kv_lens).tolist() == [capacity, 7]
