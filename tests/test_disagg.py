"""Disaggregated prefill/decode serving across the DCN tier (ISSUE 10).

The load-bearing contract (docs/disagg.md): the role-split tier —
chunked prefill on one slice, paged decode on another, KV pages
streaming between them — must be TOKEN-IDENTICAL per request to the
monolithic ``ServingEngine`` on the virtual (2,4) mesh, including a
preemption that crosses a migration and decode-side page ids permuted
vs the prefill side's; migration faults demote to monolithic serving
(never die, never silently corrupt); and the transfer protocol is
commlint-clean with a seeded violation proving the coverage is real.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.disagg import (
    DisaggConfigError, DisaggServingEngine, MigrationError,
    MigrationIntegrityError, MigrationStream, MigrationTimeoutError,
    kv_migrate_local, role_contexts, split_roles,
)
from triton_distributed_tpu.models.config import ModelConfig, tiny_config
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving import (
    AdmitResult, Request, RequestState, ServingEngine,
)


@pytest.fixture(scope="module")
def ctx24():
    """The virtual (2,4) DCN x ICI mesh over the 8 CPU devices."""
    return initialize_distributed(mesh_shape=(2, 4),
                                  axis_names=("dcn", "tp"))


@pytest.fixture(scope="module")
def model24():
    """(cfg, params) for the (2,4) parity tests — kv heads divide the
    4-way TP degree of each role slice."""
    cfg = ModelConfig(hidden_size=64, intermediate_size=96, num_layers=2,
                      num_heads=4, num_kv_heads=4, head_dim=16,
                      vocab_size=256, dtype="float32")
    return cfg, init_dense_llm(jax.random.PRNGKey(3), cfg)


@pytest.fixture(scope="module")
def mono24(ctx24, model24):
    """The monolithic parity oracle on the SAME (2,4) mesh (xla
    backend: the dcn axis replicated — the golden path)."""
    cfg, params = model24
    return Engine(cfg, params, ctx24, backend="xla", max_seq=64,
                  page_size=4)


def _disagg(ctx24, model24, **kw):
    cfg, params = model24
    return DisaggServingEngine.from_mesh(cfg, params, ctx24, max_seq=64,
                                         page_size=4, **kw)


def _serve_all(se, prompts, gens, priorities=None, max_iters=3000):
    reqs = []
    for i, (p, g) in enumerate(zip(prompts, gens)):
        pr = priorities[i] if priorities else 0
        req, res = se.submit(p, g, priority=pr)
        assert res is AdmitResult.ADMITTED
        reqs.append(req)
    se.run(max_iters=max_iters)
    return reqs


def _prompts(seed, n, lengths=(6, 9)):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, int(rng.choice(lengths))).tolist()
            for _ in range(n)]


# ---------------------------------------------------------------------------
# Request lifecycle: the MIGRATING edges.
# ---------------------------------------------------------------------------

def test_request_migrating_edges():
    r = Request(prompt=[1, 2, 3], max_new_tokens=3)
    r.advance(RequestState.PREFILLING)
    r.advance(RequestState.MIGRATING)
    r.advance(RequestState.PREEMPTED)          # preempt mid-migration
    r.advance(RequestState.PREFILLING)         # recompute-on-resume
    r.advance(RequestState.MIGRATING)
    r.advance(RequestState.RUNNING)
    with pytest.raises(ValueError, match="illegal request transition"):
        r.advance(RequestState.MIGRATING)      # RUNNING never re-migrates
    r2 = Request(prompt=[1], max_new_tokens=1)
    with pytest.raises(ValueError, match="illegal request transition"):
        r2.advance(RequestState.MIGRATING)     # WAITING must prefill first


# ---------------------------------------------------------------------------
# Role split.
# ---------------------------------------------------------------------------

def test_split_roles_partitions_the_mesh(ctx24):
    pctx, dctx = split_roles(ctx24)
    assert pctx.mesh.axis_names == ("tp",) and pctx.num_ranks == 4
    assert dctx.mesh.axis_names == ("tp",) and dctx.num_ranks == 4
    p_devs = set(pctx.mesh.devices.ravel())
    d_devs = set(dctx.mesh.devices.ravel())
    assert not (p_devs & d_devs), "roles must own disjoint devices"
    assert p_devs | d_devs == set(ctx24.mesh.devices.ravel())


def test_role_contexts_degenerate_pairs():
    """The CPU-proof helper: two devices -> disjoint 1-device roles;
    one device -> both roles share it (the transport is device-count-
    independent)."""
    pctx, dctx = role_contexts(jax.devices()[:2])
    assert pctx.mesh.devices.ravel()[0] != dctx.mesh.devices.ravel()[0]
    pctx1, dctx1 = role_contexts(jax.devices()[:1])
    assert pctx1.mesh.devices.ravel()[0] == dctx1.mesh.devices.ravel()[0]


def test_split_roles_named_errors(ctx24):
    with pytest.raises(DisaggConfigError, match="not on the mesh"):
        split_roles(ctx24, inter_axis="nope")
    ctx1 = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])
    with pytest.raises(DisaggConfigError, match="exactly 2 slices"):
        split_roles(ctx1, inter_axis="tp", axis="tp")


# ---------------------------------------------------------------------------
# The migration op (single-program shard_map form).
# ---------------------------------------------------------------------------

PAGE_ROWS = 8


def _pools():
    src = jnp.arange(4 * PAGE_ROWS * 128, dtype=jnp.float32
                     ).reshape(4 * PAGE_ROWS, 128)
    dst = -jnp.ones((6 * PAGE_ROWS, 128), jnp.float32)
    return src, dst


def test_kv_migrate_local_golden(ctx24):
    """Pages land on the decode slice at REWRITTEN ids, the source
    slice's pool is untouched, untargeted pages keep their bytes —
    on the virtual (2,4) mesh with real interpret-mode DMA chains."""
    src_pages, dst_pages = (1, 3, 0), (5, 0, 2)
    pool_src, pool_dst = _pools()
    fn = functools.partial(kv_migrate_local, src_pages=src_pages,
                           dst_pages=dst_pages, inter_axis="dcn",
                           n_inter=2, page_rows=PAGE_ROWS, block_pages=1)
    out = jax.jit(jax.shard_map(
        fn, mesh=ctx24.mesh, in_specs=(P(), P()), out_specs=P("dcn"),
        check_vma=False))(pool_src, pool_dst)
    out = np.asarray(out)
    rows = 6 * PAGE_ROWS
    s0, s1 = out[:rows], out[rows:]
    assert np.all(s0 == -1), "prefill slice's decode pool must not move"
    ps = np.asarray(pool_src)
    for sp, dp in zip(src_pages, dst_pages):
        np.testing.assert_array_equal(
            s1[dp * PAGE_ROWS:(dp + 1) * PAGE_ROWS],
            ps[sp * PAGE_ROWS:(sp + 1) * PAGE_ROWS])
    for p in set(range(6)) - set(dst_pages):
        assert np.all(s1[p * PAGE_ROWS:(p + 1) * PAGE_ROWS] == -1)


def test_kv_migrate_local_validation():
    pool_src, pool_dst = _pools()
    kw = dict(inter_axis="dcn", n_inter=2, page_rows=PAGE_ROWS)
    with pytest.raises(ValueError, match="pair one-to-one"):
        kv_migrate_local(pool_src, pool_dst, (0, 1), (2,), **kw)
    with pytest.raises(ValueError, match="duplicate destination"):
        kv_migrate_local(pool_src, pool_dst, (0, 1), (2, 2), **kw)
    with pytest.raises(ValueError, match="outside the pool"):
        kv_migrate_local(pool_src, pool_dst, (9,), (0,), **kw)
    with pytest.raises(ValueError, match="page_rows required"):
        kv_migrate_local(pool_src, pool_dst, (0,), (1,), inter_axis="dcn",
                         n_inter=2)
    # Empty stream is a no-op, not an error.
    assert kv_migrate_local(pool_src, pool_dst, (), (), **kw) is pool_dst


def test_disagg_migrate_protocol_clean():
    """The commlint registry driver: pack chain + DCN hop + scatter
    chain replayed over (2,2) and (2,4) — every DMA awaited, no
    deadlock (satellite #1; the CI lint job sweeps this with --all)."""
    from triton_distributed_tpu.analysis.registry import analyze_op

    for report in analyze_op("disagg_migrate"):
        assert report.ok, (
            f"{report.op}: " + "; ".join(v.message
                                         for v in report.violations))
        assert report.n_kernels > 0


def test_seeded_migration_violation_caught():
    """A pack chain that skips its last DMA wait (the seeded bug) is
    flagged — proof the sweep sees the migration protocol, not just
    clean replays."""
    from triton_distributed_tpu.analysis import check, trace_op

    pool_src, pool_dst = _pools()

    def driver(d):
        kv_migrate_local(pool_src, pool_dst, (1, 3, 0), (5, 0, 2),
                         inter_axis="dcn", n_inter=d["dcn"],
                         page_rows=PAGE_ROWS, block_pages=1,
                         _drop_pack_wait=True)

    report = check(trace_op(driver, axes=("dcn", "tp"), dims=(2, 4),
                            name="seeded-migration"))
    kinds = {v.kind for v in report.violations}
    assert "delta-imbalance" in kinds, report.violations


# ---------------------------------------------------------------------------
# MigrationStream (host transport) units.
# ---------------------------------------------------------------------------

def _kv_blocks(n, val=1.0):
    return [(jnp.full((2, 1, 4, 1, 8), val * (i + 1), jnp.float32),
             jnp.full((2, 1, 4, 1, 8), -val * (i + 1), jnp.float32))
            for i in range(n)]


def test_migration_stream_double_buffer_and_accounting():
    """Blocks land in order, one rotation per advance, with a send
    always a step ahead of the landing scatter (double buffer); bytes
    and pages account the whole stream."""
    landed = []
    stream = MigrationStream("r", _kv_blocks(3),
                             [[7], [2], [5]], put=lambda kv: kv,
                             verify=True)
    done = stream.advance(lambda i, kv, pages: landed.append((i, pages)))
    assert not done and landed == []          # pipeline priming: send only
    done = stream.advance(lambda i, kv, pages: landed.append((i, pages)))
    assert not done and landed == [(0, [7])]
    done = stream.advance(lambda i, kv, pages: landed.append((i, pages)))
    assert not done and landed == [(0, [7]), (1, [2])]
    done = stream.advance(lambda i, kv, pages: landed.append((i, pages)))
    assert done and landed[-1] == (2, [5])
    assert stream.pages_moved == 3
    assert stream.bytes_moved == 3 * 2 * (2 * 1 * 4 * 1 * 8) * 4


def test_migration_stream_drop_and_corrupt_named():
    def run(hook):
        stream = MigrationStream("r", _kv_blocks(2), [[0], [1]],
                                 put=lambda kv: kv, verify=True,
                                 chaos_hook=hook)
        for _ in range(4):
            if stream.advance(lambda i, kv, pages: None):
                break

    with pytest.raises(MigrationError, match="block 0 lost in transit"):
        run(lambda i, kv: None if i == 0 else kv)
    with pytest.raises(MigrationIntegrityError, match="checksum mismatch"):
        run(lambda i, kv: (kv[0] + 1.0, kv[1]) if i == 1 else kv)


def test_migration_stream_deadline_named():
    t = [0.0]
    stream = MigrationStream("r", _kv_blocks(2), [[0], [1]],
                             put=lambda kv: kv, verify=False,
                             timeout_s=10.0, clock=lambda: t[0])
    stream.advance(lambda i, kv, pages: None)
    t[0] = 11.0
    with pytest.raises(MigrationTimeoutError, match="exceeded its "
                                                    "deadline"):
        stream.advance(lambda i, kv, pages: None)
    # transient marker: the demotion path must treat all three as such
    from triton_distributed_tpu import resilience

    assert resilience.is_transient(MigrationTimeoutError("x"))
    assert resilience.is_transient(MigrationIntegrityError("x"))
    assert resilience.is_transient(MigrationError("x"))


# ---------------------------------------------------------------------------
# DisaggServingEngine: the (2,4) acceptance contract.
# ---------------------------------------------------------------------------

def test_disagg_parity_vs_monolithic_2x4(ctx24, model24, mono24):
    """THE acceptance test: the role-split tier on the (2,4) mesh is
    token-identical to the monolithic ServingEngine on the same mesh,
    with at least one migration landing at decode-side page ids that
    differ from the prefill side's 0..n-1 (the page-table rewrite)."""
    prompts = _prompts(0, 4, lengths=(6, 9, 11))
    gens = [6, 5, 7, 4]
    mono = ServingEngine(mono24, max_batch=2, prefill_chunk=4)
    mono_reqs = _serve_all(mono, prompts, gens)
    dg = _disagg(ctx24, model24, max_batch=2, prefill_chunk=4,
                 block_pages=1)
    dg_reqs = _serve_all(dg, prompts, gens)
    assert dg.disagg_active, dg.demotion_reason
    assert all(r.state is RequestState.FINISHED for r in dg_reqs)
    for m, d in zip(mono_reqs, dg_reqs):
        assert d.tokens == m.tokens, f"{d.req_id} diverged"
    assert len(dg.migrations_log) == 4        # every request migrated
    rewrites = [m for m in dg.migrations_log
                if m["src_pages"] != m["dst_pages"]]
    assert rewrites, ("every migration landed at identity ids — the "
                      "rewrite path is untested")


def test_disagg_preempt_during_migration_resume_parity(ctx24, model24,
                                                       mono24):
    """Decode-pool pressure evicts a request MID-migration (its stream
    is cancelled, pages freed); it resumes by recompute — re-prefill +
    re-migrate — and still matches the monolithic tokens."""
    prompts = [list(range(10, 16)), list(range(30, 42)),
               list(range(50, 54))]
    gens = [10, 4, 2]
    priorities = [1, 0, 0]
    mono = ServingEngine(mono24, max_batch=2, num_pages=5,
                         prefill_chunk=4)
    mono_reqs = _serve_all(mono, prompts, gens, priorities)
    dg = _disagg(ctx24, model24, max_batch=2, num_pages=5,
                 prefill_chunk=4, block_pages=1)
    dg_reqs = _serve_all(dg, prompts, gens, priorities)
    assert dg.disagg_active
    assert dg.migration_preemptions >= 1, \
        "pool sizing no longer evicts a request mid-migration"
    assert any(r.preemptions >= 1 for r in dg_reqs)
    for m, d in zip(mono_reqs, dg_reqs):
        assert d.tokens == m.tokens, \
            f"{d.req_id} diverged (preemptions={d.preemptions})"


def test_disagg_fault_demotes_to_monolithic_with_parity(ctx24, model24,
                                                        mono24):
    """A lost migration block demotes the tier to monolithic serving on
    the decode slice (named reason recorded, RUNNING work kept, the
    rest recomputed) — output still token-identical."""
    prompts = _prompts(2, 3, lengths=(6, 9))
    gens = [5, 6, 4]
    mono = ServingEngine(mono24, max_batch=2, prefill_chunk=4)
    mono_reqs = _serve_all(mono, prompts, gens)
    dg = _disagg(ctx24, model24, max_batch=2, prefill_chunk=4,
                 block_pages=1)
    fired = {"n": 0}

    def drop_once(idx, kv):
        if fired["n"] == 0:
            fired["n"] += 1
            return None
        return kv

    dg._migrate_chaos = drop_once
    with pytest.warns(RuntimeWarning, match="demoted to monolithic"):
        dg_reqs = _serve_all(dg, prompts, gens)
    assert fired["n"] == 1
    assert not dg.disagg_active
    assert "MigrationError" in dg.demotion_reason
    assert all(r.state is RequestState.FINISHED for r in dg_reqs)
    for m, d in zip(mono_reqs, dg_reqs):
        assert d.tokens == m.tokens, f"{d.req_id} diverged post-demotion"


def test_disagg_ladder_opt_out_propagates(ctx24, model24, monkeypatch):
    """TDTPU_DEMOTION_LADDER=0: the named migration error PROPAGATES
    instead of demoting (demotion must never mask a pinned config)."""
    monkeypatch.setenv("TDTPU_DEMOTION_LADDER", "0")
    dg = _disagg(ctx24, model24, max_batch=1, prefill_chunk=4,
                 block_pages=1)
    dg._migrate_chaos = lambda i, kv: None
    req, res = dg.submit([1, 2, 3, 4, 5, 6], 4)
    assert res is AdmitResult.ADMITTED
    with pytest.raises(MigrationError, match="lost in transit"):
        dg.run(max_iters=200)
    assert dg.disagg_active                    # never silently demoted


def test_disagg_config_errors(ctx24, model24):
    cfg, params = model24
    pctx, dctx = split_roles(ctx24)
    pe = Engine(cfg, params, pctx, backend="xla", max_seq=64)
    de = Engine(cfg, params, dctx, backend="xla", max_seq=64, page_size=4)
    other = tiny_config()
    pe_other = Engine(other, init_dense_llm(jax.random.PRNGKey(0), other),
                      pctx, backend="xla", max_seq=64)
    with pytest.raises(DisaggConfigError, match="different model"):
        DisaggServingEngine(pe_other, de)
    pe_short = Engine(cfg, params, pctx, backend="xla", max_seq=32)
    with pytest.raises(DisaggConfigError, match="max_seq"):
        DisaggServingEngine(pe_short, de)
    with pytest.raises(DisaggConfigError, match="block_pages"):
        DisaggServingEngine(pe, de, block_pages=0)


def test_disagg_metrics_and_report_lane(ctx24, model24, tmp_path):
    """Under an obs run the migration lane publishes bytes/pages/count
    counters and the latency histogram, obs.report renders the section,
    and a FAILED stream gates --check unless explicitly allowed."""
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import report as obs_report

    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir)
    try:
        dg = _disagg(ctx24, model24, max_batch=2, prefill_chunk=4)
        _serve_all(dg, _prompts(4, 2), [4, 5])
        reg = obs_metrics.registry()
        assert reg.get(obs_metrics.KV_MIGRATIONS).value == 2
        assert reg.get(obs_metrics.KV_MIGRATE_BYTES).value > 0
        assert reg.get(obs_metrics.KV_MIGRATE_PAGES).value >= 2
        assert reg.get(obs_metrics.KV_MIGRATE_LATENCY_MS).count == 2
        assert reg.get(obs_metrics.KV_MIGRATE_FAILURES) is None
        # Now a failed stream -> failure counter + disagg demotion.
        dg2 = _disagg(ctx24, model24, max_batch=1, prefill_chunk=4)
        dg2._migrate_chaos = lambda i, kv: None
        with pytest.warns(RuntimeWarning, match="demoted to monolithic"):
            _serve_all(dg2, [_prompts(5, 1)[0]], [4])
        assert reg.get(obs_metrics.KV_MIGRATE_FAILURES).value == 1
        assert reg.get(obs_metrics.DISAGG_DEMOTIONS).value == 1
    finally:
        obs.finish_run()
    out = obs_report.main([run_dir, "--allow-slo-violations"])
    assert out == 0                            # render-only never gates
    rc = obs_report.main([run_dir, "--check", "--allow-slo-violations",
                          "--allow-preemptions", "--require-series",
                          obs_metrics.KV_MIGRATE_BYTES])
    assert rc == 1                             # the failed stream gates
    rc = obs_report.main([run_dir, "--check", "--allow-slo-violations",
                          "--allow-preemptions",
                          "--allow-migration-failures",
                          "--require-series",
                          obs_metrics.KV_MIGRATE_BYTES])
    assert rc == 0
