"""Hierarchical DCN×ICI overlap subsystem (ops/hierarchical.py).

Golden parity + BIT-MATCH vs the unfused two-level compositions on the
(2, 4) virtual mesh (ISSUE 2 acceptance), commlint coverage of the
two-tier protocol (clean library + a seeded violation the checker must
catch), the perf-model DCN crossover, and Engine auto-selection on 2-axis
meshes with the 1-axis fallback.

The degenerate-intra tests ((n_inter, 1) meshes) exercise the SAME DCN
rotation/ring machinery with the intra tier collapsed to the Pallas
compute core — they stay meaningful on jax builds whose interpreter
cannot emulate cross-device DMA (where the (2, 4) Pallas-tier cases fail
environmentally, like their two_level siblings).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.hierarchical import (
    ag_gemm_2d,
    gemm_rs_2d,
    slice_consumer_tiles,
    sp_ag_attention_2d,
)
from triton_distributed_tpu.ops.allgather_gemm import AGGemmConfig, ag_gemm_local
from triton_distributed_tpu.ops.gemm_reduce_scatter import GemmRSConfig, gemm_rs_local
from triton_distributed_tpu.runtime.context import (
    initialize_distributed, shard_map_on,
)


@pytest.fixture(scope="module")
def ctx2d():
    """(dcn=2, tp=4) mesh over the 8 virtual CPU devices."""
    return initialize_distributed(mesh_shape=(2, 4),
                                  axis_names=("dcn", "tp"))


@pytest.fixture(scope="module")
def ctx_dcn4():
    """(dcn=4, tp=1): real DCN rotation, degenerate Pallas tier."""
    return initialize_distributed(devices=jax.devices()[:4],
                                  mesh_shape=(4, 1),
                                  axis_names=("dcn", "tp"))


# ---------------------------------------------------------------------------
# Golden parity on the (2, 4) mesh (full two-tier).
# ---------------------------------------------------------------------------

def test_ag_gemm_2d_golden(ctx2d):
    N, m, k, cols = 8, 16, 128, 128
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((N * m, k)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, 4 * cols)) * 0.1, jnp.float32)
    out = ag_gemm_2d(a, b, ctx2d)
    ref = np.asarray(a) @ np.asarray(b)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)


def test_gemm_rs_2d_golden(ctx2d):
    N, m, cols = 8, 32, 128
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.standard_normal((m, N * 64)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((N * 64, cols)) * 0.1, jnp.float32)
    out = gemm_rs_2d(a, b, ctx2d)
    ref = np.asarray(a) @ np.asarray(b)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)


def test_sp_ag_attention_2d_pipelined_golden(ctx2d):
    """The PIPELINED hierarchical SP attention (per-slice flash merges
    under the DCN rotation) matches the dense causal golden."""
    from triton_distributed_tpu.ops.flash_attention import _block_attn

    b, s, hq, hkv, d = 1, 256, 4, 2, 64
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)) * 0.3, jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)) * 0.3, jnp.float32)
    out = np.asarray(sp_ag_attention_2d(q, k, v, ctx2d))
    acc, _, l = _block_attn(q, k, v, jnp.tril(jnp.ones((s, s), bool)))
    gold = np.asarray(acc / jnp.maximum(l, 1e-30)[..., None])
    np.testing.assert_allclose(out, gold, rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# Bit-match vs the unfused two-level compositions (ISSUE 2 acceptance).
# The compositions run the SAME per-slice primitives in the SAME order —
# only the DCN leg is unfused (one blocking all_gather instead of the
# pipelined rotation) — so equality is exact, not tolerance-washed.
# ---------------------------------------------------------------------------

def test_ag_gemm_2d_bitmatch_unfused(ctx2d):
    n_inter, n_intra = 2, 4
    m, k, cols = 16, 128, 128
    N = n_inter * n_intra
    rng = np.random.default_rng(3)
    a = jnp.asarray(rng.standard_normal((N * m, k)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n_intra * cols)) * 0.1,
                    jnp.float32)
    cfg = AGGemmConfig()
    fused = ag_gemm_2d(a, b, ctx2d, cfg=cfg)

    def unfused(x_local, b_local):
        """Intra fused leg + BLOCKING DCN all_gather + the same per-slice
        consumer GEMM (same tiles via slice_consumer_tiles)."""
        from triton_distributed_tpu.ops.gemm import pallas_matmul

        me_inter = jax.lax.axis_index("dcn")
        own, block = ag_gemm_local(x_local, b_local, axis="tp",
                                   num_ranks=n_intra, cfg=cfg,
                                   return_gathered=True)
        blocks = jax.lax.all_gather(block, "dcn")     # (n_inter, ...)
        tm, tn, tk = slice_consumer_tiles(n_intra * m, k, cols,
                                          x_local.dtype, cfg)
        outs = []
        for s in range(n_inter):
            o = pallas_matmul(blocks[s], b_local, tile_m=tm, tile_n=tn,
                              tile_k=tk)
            outs.append(jnp.where(s == me_inter, own, o))
        return jnp.concatenate(outs, axis=0)

    jfn = shard_map_on(ctx2d, unfused, (P(("dcn", "tp")), P(None, "tp")),
                       P(None, "tp"))
    ref = jax.jit(jfn)(a, b)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(ref))


def test_gemm_rs_2d_bitmatch_unfused(ctx2d):
    n_inter, n_intra = 2, 4
    N = n_inter * n_intra
    m, cols = 32, 128
    rng = np.random.default_rng(4)
    a = jnp.asarray(rng.standard_normal((m, N * 64)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((N * 64, cols)) * 0.1, jnp.float32)
    cfg = GemmRSConfig()
    fused = gemm_rs_2d(a, b, ctx2d, cfg=cfg)

    def unfused(x_local, b_local):
        """Per-chunk fused intra GEMM+RS, then an UNFUSED DCN leg: gather
        every slice's chunk and sum in the ring's arrival order
        (me+1, me+2, …, me) — the order dcn_ring_reduce documents."""
        me = jax.lax.axis_index("dcn")
        slice_rows = n_intra * (m // N)
        qs = []
        for c in range(n_inter):
            rows = jax.lax.dynamic_slice_in_dim(
                x_local, jnp.int32(c) * slice_rows, slice_rows, axis=0)
            qs.append(gemm_rs_local(rows, b_local, axis="tp",
                                    num_ranks=n_intra, cfg=cfg))
        stacked = jnp.stack(qs)                         # [c] = my q_c
        gathered = jax.lax.all_gather(stacked, "dcn")   # [a, c] = slice a's q_c
        # Sum my chunk (c = me) over sources a = me+1 … me+n_inter (mod) —
        # the ring's arrival order.
        acc = None
        for s in range(1, n_inter + 1):
            src = jax.lax.rem(me + s, n_inter)
            contrib = jnp.take(jnp.take(gathered, src, axis=0), me, axis=0)
            acc = contrib if acc is None else acc + contrib
        return acc

    jfn = shard_map_on(ctx2d, unfused,
                       (P(None, ("dcn", "tp")), P(("dcn", "tp"))),
                       P(("dcn", "tp")))
    ref = jax.jit(jfn)(a, b)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(ref))


# ---------------------------------------------------------------------------
# Degenerate-intra meshes: the DCN pipeline machinery itself.
# ---------------------------------------------------------------------------

def test_ag_gemm_2d_dcn_rotation_golden(ctx_dcn4):
    N, m, k, cols = 4, 16, 128, 128
    rng = np.random.default_rng(5)
    a = jnp.asarray(rng.standard_normal((N * m, k)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, cols)) * 0.1, jnp.float32)
    out = ag_gemm_2d(a, b, ctx_dcn4)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(a) @ np.asarray(b),
                               rtol=1e-3, atol=1e-3)


def test_gemm_rs_2d_dcn_ring_golden(ctx_dcn4):
    N, m, cols = 4, 32, 128
    rng = np.random.default_rng(6)
    a = jnp.asarray(rng.standard_normal((m, N * 64)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((N * 64, cols)) * 0.1, jnp.float32)
    out = gemm_rs_2d(a, b, ctx_dcn4)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(a) @ np.asarray(b),
                               rtol=1e-3, atol=1e-3)


def test_sp_ag_attention_2d_dcn_rotation_golden(ctx_dcn4):
    from triton_distributed_tpu.ops.flash_attention import _block_attn

    b, s, hq, hkv, d = 1, 128, 4, 2, 64
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((b, s, hq, d)) * 0.3, jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)) * 0.3, jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)) * 0.3, jnp.float32)
    out = np.asarray(sp_ag_attention_2d(q, k, v, ctx_dcn4))
    acc, _, l = _block_attn(q, k, v, jnp.tril(jnp.ones((s, s), bool)))
    gold = np.asarray(acc / jnp.maximum(l, 1e-30)[..., None])
    np.testing.assert_allclose(out, gold, rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# commlint: the two-tier protocol is covered.
# ---------------------------------------------------------------------------

def test_hierarchical_protocol_clean():
    from triton_distributed_tpu.analysis.registry import analyze_op

    for report in analyze_op("hierarchical"):
        assert report.ok, (
            f"{report.op}: " + "; ".join(v.message for v in report.violations))
        assert report.n_kernels > 0


@pytest.mark.slow
def test_hierarchical_sp_protocol_clean():
    """Replays per-rank flash partials per chunk (~15 s) — the CI commlint
    sweep (`--all`) covers this op every run; tier-1 keeps the cheap
    `hierarchical` clean test + the seeded-violation test below."""
    from triton_distributed_tpu.analysis.registry import analyze_op

    for report in analyze_op("hierarchical_sp"):
        assert report.ok, (
            f"{report.op}: " + "; ".join(v.message for v in report.violations))
        assert report.n_events > 0


def test_seeded_two_tier_violation_caught():
    """A broken intra-slice wait delta INSIDE the DCN rotation is caught —
    proof the checker sees through the two-tier composition, not just
    flat 1-D launches."""
    from jax.experimental.pallas import tpu as pltpu
    import jax.experimental.pallas as pl

    from triton_distributed_tpu.analysis import check, trace_op
    from triton_distributed_tpu.language import shmem_device as shmem
    from triton_distributed_tpu import language as dl
    from triton_distributed_tpu.language.core import any_spec, kernel_call

    def bad_intra_ag(n, axis, x_ref, out_ref, send_sems, recv_sem):
        me = dl.rank(axis)
        shmem.barrier_all(axis)
        my_slot = out_ref.at[pl.ds(me * x_ref.shape[0], x_ref.shape[0])]
        handles = []
        for i in range(n - 1):
            peer = jax.lax.rem(me + 1 + i, n)
            handles.append(shmem.putmem_nbi_block(
                x_ref, my_slot, send_sems.at[i], recv_sem, peer, axis))
        shmem.quiet(*handles)
        shmem.wait_deliveries(x_ref, recv_sem, n - 2)   # BUG: n-1 deliveries

    def driver(dims):
        n_inter, n_intra = dims["dcn"], dims["tp"]
        x = jnp.asarray(np.ones((16, 128), np.float32))
        kernel = functools.partial(bad_intra_ag, n_intra, "tp")
        call = kernel_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n_intra * 16, 128), jnp.float32),
            in_specs=[any_spec()],
            out_specs=any_spec(),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((max(n_intra - 1, 1),)),
                pltpu.SemaphoreType.DMA(()),
            ],
            uses_barrier=True,
        )
        # The broken intra kernel runs under the DCN rotation, exactly
        # like the hierarchical ops' slice pipeline.
        block = call(x)
        perm = tuple((i, (i + 1) % n_inter) for i in range(n_inter))
        for _ in range(n_inter - 1):
            block = jax.lax.ppermute(block, "dcn", perm)
            call(x)

    report = check(trace_op(driver, axes=("dcn", "tp"), dims=(2, 4),
                            name="seeded-two-tier"))
    kinds = {v.kind for v in report.violations}
    assert "delta-imbalance" in kinds, report.violations


# ---------------------------------------------------------------------------
# Perf model: the DCN-tier crossover.
# ---------------------------------------------------------------------------

def test_pick_mode_dcn_crossover():
    from triton_distributed_tpu.layers.tp_mlp import pick_mode

    kw = dict(hidden=4096, ffn=12288, itemsize=2)
    # Large prefill: the hierarchical path wins over slice-replication.
    assert pick_mode("auto", 8192, 4, n_inter=2, **kw) == "overlap2d"
    # Small row counts: the 10 µs/hop DCN latency sinks it — AUTO declines.
    assert pick_mode("auto", 64, 4, n_inter=2, **kw) != "overlap2d"
    # 1-axis mesh: never.
    assert pick_mode("auto", 8192, 4, **kw) != "overlap2d"
    # Degenerate-intra (n_inter, 1) mesh: the joint degree gates the 2d
    # candidate, and the replicated candidate is charged its DCN AR —
    # hierarchical must be reachable at n=1 (review finding r6).
    assert pick_mode("auto", 8192, 1, n_inter=4, **kw) == "overlap2d"
    assert pick_mode("auto", 16, 1, n_inter=4, **kw) == "ar"


def test_perf_model_2d_estimates_monotone():
    from triton_distributed_tpu.runtime.perf_model import (
        ag_gemm_2d_time_s, ag_gemm_time_s, gemm_rs_2d_time_s,
    )

    # More DCN hops cost more; n_inter=1 degenerates to the intra estimate.
    t1 = ag_gemm_2d_time_s(4096, 4096, 4096, 4, 1, 2)
    t2 = ag_gemm_2d_time_s(4096, 4096, 4096, 4, 2, 2)
    assert t1 == ag_gemm_time_s(4096, 4096, 4096, 4, 2)
    assert t2 > 0
    assert gemm_rs_2d_time_s(4096, 4096, 4096, 4, 2, 2) \
        > gemm_rs_2d_time_s(4096, 4096, 4096, 4, 1, 2) * 0.5


# ---------------------------------------------------------------------------
# Engine auto-selection.
# ---------------------------------------------------------------------------

def _tiny_cfg():
    from triton_distributed_tpu.models.config import ModelConfig

    return ModelConfig(hidden_size=128, intermediate_size=256, num_layers=1,
                       num_heads=4, num_kv_heads=2, head_dim=32,
                       vocab_size=64, dtype="float32")


def test_engine_selects_hierarchical_on_2axis_mesh():
    """On a (dcn, tp) mesh the Engine shards params/cache over BOTH tiers
    and prefill resolves to overlap2d; token-identical to the single-chip
    XLA engine (degenerate-intra mesh so the check runs everywhere)."""
    from triton_distributed_tpu.models.dense import init_dense_llm
    from triton_distributed_tpu.models.engine import Engine

    cfg = _tiny_cfg()
    params = init_dense_llm(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(np.arange(1, 17)[None, :], jnp.int32)

    ctx2 = initialize_distributed(devices=jax.devices()[:2],
                                  mesh_shape=(2, 1),
                                  axis_names=("dcn", "tp"))
    eng = Engine(cfg, params, ctx2, backend="overlap", max_seq=32)
    assert eng.hierarchical
    assert eng.n_total == 2
    assert eng._prefill_mode(1, 16) == "overlap2d"
    toks = np.asarray(eng.serve(ids, gen_len=3))

    ctx1 = initialize_distributed(devices=jax.devices()[:1],
                                  mesh_shape=(1,), axis_names=("tp",))
    eng1 = Engine(cfg, params, ctx1, backend="xla", max_seq=32)
    toks1 = np.asarray(eng1.serve(ids, gen_len=3))
    np.testing.assert_array_equal(toks, toks1)


def test_engine_1axis_never_hierarchical():
    """Perf-model fallback: a 1-axis mesh never resolves overlap2d, and
    the engine stays on the single-axis layout."""
    from triton_distributed_tpu.models.dense import init_dense_llm
    from triton_distributed_tpu.models.engine import Engine

    cfg = _tiny_cfg()
    params = init_dense_llm(jax.random.PRNGKey(0), cfg)
    ctx1 = initialize_distributed(devices=jax.devices()[:1],
                                  mesh_shape=(1,), axis_names=("tp",))
    eng = Engine(cfg, params, ctx1, backend="auto", max_seq=32)
    assert not eng.hierarchical
    assert eng.n_inter == 1
    assert eng.shard_axes == "tp"
    assert eng._prefill_mode(1, 16) != "overlap2d"


def test_engine_2axis_full_mesh_selects(ctx2d):
    """(2,4): selection + joint sharding resolve without running the
    Pallas tier (mode resolution and spec construction only)."""
    from triton_distributed_tpu.models.dense import init_dense_llm
    from triton_distributed_tpu.models.engine import Engine

    import dataclasses

    # kv heads must divide the JOINT TP degree 8 on (2, 4).
    cfg = dataclasses.replace(_tiny_cfg(), num_heads=8, num_kv_heads=8,
                              head_dim=16)
    params = init_dense_llm(jax.random.PRNGKey(0), cfg)
    eng = Engine(cfg, params, ctx2d, backend="overlap", max_seq=32)
    assert eng.hierarchical and eng.n_total == 8
    assert eng.shard_axes == ("dcn", "tp")
    assert eng._prefill_mode(2, 16) == "overlap2d"
