"""Elastic fleet (ISSUE 11, docs/resilience.md "Fleet degradation").

The load-bearing contracts: a confirmed-dead rank EVACUATES the serving
tier to the survivor sub-mesh with per-request token parity and intact
first-submission accounting; a slow-but-alive rank (straggler) only
narrows admission (flap damping — never evicted); the rejoin probe
re-expands to the full mesh once the loss clears; and
``TDTPU_DEMOTION_LADDER=0`` propagates the named ``RankLossError``
instead of changing geometry.
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.models.config import tiny_config
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.resilience import (
    CommTimeoutError, FaultClass, FaultInjectionError, RankLossError,
    chaos, clear_rank_loss, fleet, lost_ranks, mark_rank_lost,
)
from triton_distributed_tpu.resilience.faults import FaultPlan
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving.loop import ServingEngine


@pytest.fixture(autouse=True)
def _clean_rank_registry():
    clear_rank_loss()
    yield
    clear_rank_loss()


@pytest.fixture()
def fresh_registry():
    return obs_metrics.set_registry(obs_metrics.Registry())


@pytest.fixture(scope="module")
def tiny():
    cfg = tiny_config()
    return cfg, init_dense_llm(jax.random.PRNGKey(7), cfg)


def _ctx2():
    return initialize_distributed(mesh_shape=(2,), axis_names=("tp",),
                                  devices=jax.devices()[:2])


def _golden(cfg, params, ctx, prompts, gens):
    oracle = Engine(cfg, params, ctx, backend="xla", max_seq=64)
    return [np.asarray(oracle.serve(jnp.asarray([p], jnp.int32),
                                    gen_len=g))[0].tolist()
            for p, g in zip(prompts, gens)]


# ---------------------------------------------------------------------------
# The rank_loss fault class (faults.py).
# ---------------------------------------------------------------------------

def test_rank_loss_matrix_case_detected():
    """The replay lane: a rank_loss plan fails every pallas_call on the
    target rank with the NAMED RankLossError (persistent, unlike the
    one-shot crash) — the chaos matrix expects detection."""
    from triton_distributed_tpu.analysis.registry import build_registry

    driver = build_registry((2,))["allreduce"]
    baseline = chaos._clean_baseline(driver, ("tp",), (2,), "allreduce@2")
    case = chaos.run_case("allreduce", ("tp",), (2,),
                          FaultClass.RANK_LOSS, seed=0,
                          baseline_hashes=baseline, driver=driver)
    assert case.ok and case.verdict == "detected"
    text = "\n".join(case.diagnostics)
    assert "RankLossError" in text and "rank 0" in text


def test_rank_loss_plan_is_persistent_and_scopes_registry():
    plan = FaultPlan(FaultClass.RANK_LOSS, target_rank=3)
    assert plan.persistent            # forced: a dead chip stays dead
    assert 3 not in lost_ranks()
    with plan.active():
        assert 3 in lost_ranks()      # host-visible while active
    assert 3 not in lost_ranks()      # scope exit clears the mark
    # Explicit marks are sticky until cleared (the chaos kill switch).
    mark_rank_lost(5)
    assert 5 in lost_ranks()
    clear_rank_loss(5)
    assert 5 not in lost_ranks()


def test_crash_diagnostics_name_the_rank():
    """ISSUE 11 satellite: crash events/errors carry the logical rank —
    attribution without parsing kernel names."""
    from triton_distributed_tpu.analysis.registry import build_registry

    driver = build_registry((2,))["allreduce"]
    baseline = chaos._clean_baseline(driver, ("tp",), (2,), "allreduce@2")
    case = chaos.run_case("allreduce", ("tp",), (2,), FaultClass.CRASH,
                          seed=0, baseline_hashes=baseline, driver=driver)
    assert case.ok
    text = "\n".join(case.diagnostics)
    assert "on rank 0" in text        # the fired-fault detail
    assert "rank=0" in text           # the structured error message


def test_attribute_rank_walks_the_chain():
    assert fleet.attribute_rank(RankLossError("x", rank=2)) == 2
    assert fleet.attribute_rank(
        CommTimeoutError(sem="s", rank=1, expected=1, observed=0,
                         waited_s=1.0, timeout_s=1.0)) == 1
    outer = RuntimeError("wrapped")
    outer.__cause__ = FaultInjectionError("inner", rank=4)
    assert fleet.attribute_rank(outer) == 4
    assert fleet.attribute_rank(ValueError("no rank")) is None


# ---------------------------------------------------------------------------
# Per-rank comm-timeout metrics (satellite).
# ---------------------------------------------------------------------------

def test_comm_timeouts_counted_per_rank(tmp_path):
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.resilience import deadline

    obs.start_run(str(tmp_path / "run"))
    try:
        reg = obs_metrics.registry()
        deadline.record_timeout(sem="t/sem", rank=3, expected=2,
                                observed=0, waited_s=0.1)
        deadline.record_timeout(sem="t/sem", rank=3, expected=2,
                                observed=0, waited_s=0.1)
        deadline.record_timeout(sem="t/sem2", rank=0, expected=1,
                                observed=0, waited_s=0.1)
        c3 = reg.get('tdtpu_comm_timeouts_total{rank="3"}')
        c0 = reg.get('tdtpu_comm_timeouts_total{rank="0"}')
        assert c3.value == 2 and c0.value == 1
        assert 'rank="3"' in c3.to_prometheus()
        snap = reg.snapshot()
        assert snap['tdtpu_comm_timeouts_total{rank="3"}']["labels"] == \
            {"rank": "3"}
    finally:
        obs.finish_run()
    deadline.drain_timeout_events()


# ---------------------------------------------------------------------------
# Health ledger: scoring, flap damping, verdicts.
# ---------------------------------------------------------------------------

def test_ledger_timeouts_strike_the_waiters_peer():
    """A CommTimeoutError names the WAITING rank — which proved its own
    liveness by raising. The strike lands on the unique peer (the
    producer that never signalled); with >1 peer the guilt is ambiguous
    and only soft suspicion spreads (never a dead verdict)."""
    led = fleet.HealthLedger([0, 1], dead_after=2)
    assert led.observe_timeout(0, sem="s0") == 1
    assert led.verdict(1) is fleet.HealthVerdict.SUSPECT
    assert led.verdict(0) is fleet.HealthVerdict.HEALTHY  # not the waiter
    assert led.observe_timeout(0, sem="s1") == 1
    assert led.verdict(1) is fleet.HealthVerdict.DEAD
    assert led.dead() == [1] and led.alive() == [0]
    led.absolve(1)
    assert led.verdict(1) is fleet.HealthVerdict.HEALTHY
    # Ambiguous complement (4 ranks): soft suspicion only — repeated
    # expiries can never evacuate a rank they cannot pinpoint.
    led4 = fleet.HealthLedger([0, 1, 2, 3], dead_after=2)
    for _ in range(10):
        assert led4.observe_timeout(0) is None
    assert led4.dead() == []
    assert set(led4.suspects()) == {1, 2, 3}


def test_ledger_straggles_never_kill_and_decay():
    """Flap damping: soft evidence saturates at SUSPECT — a straggler
    degrades admission width, never membership — and decays on clean
    iterations so a recovered rank re-earns its width."""
    led = fleet.HealthLedger([0, 1], dead_after=2, decay=0.25)
    for _ in range(50):
        led.observe_straggle(1)
    assert led.verdict(1) is fleet.HealthVerdict.SUSPECT
    assert led.dead() == []           # soft evidence can never evacuate
    for _ in range(200):
        led.observe_clean()
    assert led.verdict(1) is fleet.HealthVerdict.HEALTHY
    # rank_loss is the hard signal: immediately dead.
    led.sync_lost({1})
    assert led.verdict(1) is fleet.HealthVerdict.DEAD


def test_ledger_error_attribution_routes_evidence():
    led = fleet.HealthLedger([0, 1], dead_after=2)
    assert led.observe_error(RankLossError("gone", rank=1)) == 1
    assert led.verdict(1) is fleet.HealthVerdict.DEAD
    assert led.observe_error(ValueError("not ours")) is None
    assert led.observe_error(RankLossError("other mesh", rank=9)) is None
    # A CommTimeoutError blames the waiter's PEER, not the waiter.
    led2 = fleet.HealthLedger([0, 1], dead_after=2)
    blamed = led2.observe_error(
        CommTimeoutError(sem="s", rank=0, expected=1, observed=0,
                         waited_s=1.0, timeout_s=1.0))
    assert blamed == 1
    assert led2.verdict(0) is fleet.HealthVerdict.HEALTHY
    # ...and the dispatch follows the chain element that CARRIED the
    # rank: a timeout wrapped by the jit runtime must not be classified
    # as a crash against the provably-alive waiter.
    led3 = fleet.HealthLedger([0, 1], dead_after=2)
    wrapped = RuntimeError("jit wrapper")
    wrapped.__cause__ = CommTimeoutError(sem="s", rank=0, expected=1,
                                         observed=0, waited_s=1.0,
                                         timeout_s=1.0)
    assert led3.observe_error(wrapped) == 1     # the peer, not rank 0
    assert led3.verdict(0) is fleet.HealthVerdict.HEALTHY
    assert led3.health(0).crashes == 0


def test_survivor_context_largest_valid_tp(ctx):
    """TP=8 loses one rank -> the largest kv-head-divisible survivor is
    TP=4 (never TP=7), reusing the sub-context mechanics."""
    sub = fleet.survivor_context(ctx, [1], num_kv_heads=8)
    assert sub.axis_size("tp") == 4
    ids = [int(d.id) for d in np.asarray(sub.mesh.devices).ravel()]
    assert 1 not in ids
    assert fleet.survivor_context(ctx, list(range(8)),
                                  num_kv_heads=8) is None


# ---------------------------------------------------------------------------
# Serving-tier evacuation / rejoin (the tentpole round-trip).
# ---------------------------------------------------------------------------

def test_evacuation_roundtrip_parity_accounting_rejoin(
        tiny, fresh_registry, monkeypatch, ctx):
    """The full ladder: rank loss mid-serve -> evacuation to the TP=1
    survivor mesh (requests preempted, engine re-partitioned, params
    host-resharded, jits rebuilt) -> token parity + first-submission
    TTFT kept + evacuation preemptions counted APART from pool-pressure
    preemptions -> fault clears -> rejoin probe re-expands to TP=2 with
    post-rejoin parity."""
    from triton_distributed_tpu.obs.slo import SLOConfig
    from triton_distributed_tpu.runtime.context import set_context

    cfg, params = tiny
    monkeypatch.setenv("TDTPU_REJOIN_AFTER", "3")
    try:
        ctx2 = _ctx2()
        prompts = [[5, 77, 131, 9, 40, 2], [200, 9, 31, 7], [8, 8, 8, 9]]
        gens = [5, 4, 3]
        golden = _golden(cfg, params, ctx2, prompts, gens)
        eng = Engine(cfg, params, ctx2, backend="xla", max_seq=64,
                     page_size=4)
        se = ServingEngine(eng, max_batch=2, prefill_chunk=4,
                           slo_cfg=SLOConfig())
        reqs = [se.submit(p, g, req_id=f"fl-{i}")[0]
                for i, (p, g) in enumerate(zip(prompts, gens))]
        for _ in range(4):
            se.step()
        ttft_before = {r.req_id: r.t_first_token for r in reqs
                       if r.t_first_token is not None}
        assert ttft_before, "no first token before the kill — the test "\
                            "no longer exercises mid-serve loss"
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            mark_rank_lost(1)
            se.run()
        assert se.evacuated and eng.n_total == 1
        assert [r.tokens for r in reqs] == golden
        # Accounting: first-submission TTFT survives the evacuation...
        for r in reqs:
            if r.req_id in ttft_before:
                assert r.t_first_token == ttft_before[r.req_id]
        # ...and fleet preemptions are a DISTINCT series from
        # pool-pressure preemptions (satellite).
        reg = fresh_registry
        assert se.evacuation_preemptions >= 1
        assert reg.get(obs_metrics.SERVE_EVAC_PREEMPTIONS).value == \
            se.evacuation_preemptions
        pool = reg.get(obs_metrics.SERVE_PREEMPTIONS)
        assert pool is None or pool.value == 0
        assert reg.get(obs_metrics.FLEET_EVACUATIONS).value == 1
        assert reg.get(obs_metrics.FLEET_RANKS_ALIVE).value == 1
        assert se.fleet_log[0]["event"] == "evacuation"
        assert se.fleet_log[0]["from_ranks"] == 2
        assert se.fleet_log[0]["to_ranks"] == 1
        # The fault clears -> after TDTPU_REJOIN_AFTER clean iterations
        # the probe re-expands to the full mesh, with parity.
        clear_rank_loss(1)
        post, _ = se.submit(prompts[0], gens[0], req_id="fl-post")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            se.run()
        assert not se.evacuated and eng.n_total == 2
        assert post.tokens == golden[0]
        assert reg.get(obs_metrics.FLEET_REJOINS).value == 1
        assert reg.get(obs_metrics.FLEET_RANKS_ALIVE).value == 2
        assert [e["event"] for e in se.fleet_log] == \
            ["evacuation", "rejoin"]
    finally:
        set_context(ctx)


def test_flap_damping_straggler_shrinks_admission_never_evacuates(
        tiny, fresh_registry, ctx):
    """Satellite: a persistent straggler (the rotating resolve_straggler
    form) raises suspicion and narrows admit_cap but NEVER triggers
    evacuation; a true rank_loss then evacuates deterministically."""
    from triton_distributed_tpu.language.distributed_ops import (
        resolve_straggler,
    )
    from triton_distributed_tpu.runtime.context import set_context

    cfg, params = tiny
    try:
        ctx2 = _ctx2()
        eng = Engine(cfg, params, ctx2, backend="xla", max_seq=64,
                     page_size=4)
        se = ServingEngine(eng, max_batch=2, prefill_chunk=4)
        se.submit(list(range(10, 16)), 8, req_id="st-0")
        se.submit(list(range(30, 36)), 8, req_id="st-1")
        cap0 = se.sched.admit_cap
        for _ in range(6):
            # The rotating-resolver form with a static call_index (the
            # fused-op usage: rank call_index % n straggles) — one rank
            # persistently lagging, observed every iteration.
            rank, _ = resolve_straggler(("rotate", 64), 2, 1)
            se.fleet.observe_straggle(int(rank))
            se.step()
        assert se.sched.admit_cap < cap0          # width degraded...
        assert not se.evacuated and eng.n_total == 2   # ...not membership
        assert se.fleet.dead() == []
        # A true rank_loss evacuates, deterministically.
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            mark_rank_lost(1)
            se.run()
        assert se.evacuated and eng.n_total == 1
    finally:
        set_context(ctx)


def test_ladder_disabled_propagates_named_error(tiny, ctx):
    from triton_distributed_tpu.runtime.context import set_context

    cfg, params = tiny
    try:
        ctx2 = _ctx2()
        eng = Engine(cfg, params, ctx2, backend="xla", max_seq=64,
                     page_size=4)
        se = ServingEngine(eng, max_batch=2, prefill_chunk=4)
        se.submit([1, 2, 3, 4], 2)
        mark_rank_lost(1)
        import os

        old = os.environ.get("TDTPU_DEMOTION_LADDER")
        os.environ["TDTPU_DEMOTION_LADDER"] = "0"
        try:
            with pytest.raises(RankLossError, match="confirmed dead"):
                se.step()
        finally:
            if old is None:
                os.environ.pop("TDTPU_DEMOTION_LADDER", None)
            else:
                os.environ["TDTPU_DEMOTION_LADDER"] = old
        assert not se.evacuated and eng.n_total == 2
    finally:
        set_context(ctx)


def test_disagg_prefill_rank_loss_demotes_to_monolithic(tiny, ctx):
    """A dead PREFILL-role rank mid-migration: the disagg tier demotes
    to monolithic serving on the decode slice (no survivor geometry to
    keep), finishing with token parity."""
    from triton_distributed_tpu.disagg import (
        DisaggServingEngine, role_contexts,
    )
    from triton_distributed_tpu.runtime.context import set_context

    cfg, params = tiny
    try:
        ctx1 = initialize_distributed(mesh_shape=(1,),
                                      axis_names=("tp",),
                                      devices=jax.devices()[:1])
        prompts = [[5, 77, 131, 9, 40, 2], [200, 9, 31, 7]]
        gens = [4, 3]
        golden = _golden(cfg, params, ctx1, prompts, gens)
        pctx, dctx = role_contexts(jax.devices()[:2])
        p_id = int(np.asarray(pctx.mesh.devices).ravel()[0].id)
        pe = Engine(cfg, params, pctx, backend="xla", max_seq=64)
        de = Engine(cfg, params, dctx, backend="xla", max_seq=64,
                    page_size=4)
        se = DisaggServingEngine(pe, de, max_batch=2, prefill_chunk=4,
                                 block_pages=1)
        reqs = [se.submit(p, g, req_id=f"dgf-{i}")[0]
                for i, (p, g) in enumerate(zip(prompts, gens))]
        it = 0
        while not se._streams and it < 50:
            se.step()
            it += 1
        assert se._streams, "no migration in flight at the kill point"
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            mark_rank_lost(p_id)
            se.run(max_iters=2000)
        assert not se.disagg_active
        assert "lost" in se.demotion_reason
        assert [r.tokens for r in reqs] == golden
        assert all(r.state.name == "FINISHED" for r in reqs)
    finally:
        set_context(ctx)


# ---------------------------------------------------------------------------
# obs.report fleet lane (satellite).
# ---------------------------------------------------------------------------

def test_report_fleet_lane_and_evacuation_check(tmp_path):
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import report as obs_report

    obs.start_run(str(tmp_path / "run"))
    reg = obs_metrics.registry()
    reg.counter(obs_metrics.FLEET_EVACUATIONS, "evacs").inc()
    reg.gauge(obs_metrics.FLEET_RANKS_ALIVE, "alive").set(1)
    reg.counter(obs_metrics.COMM_TIMEOUTS, "timeouts",
                labels={"rank": "1"}).inc(3)
    run_dir = obs.finish_run()

    metrics = obs_report.load_metrics(run_dir)
    assert obs_report.evacuation_debt(metrics) == 1
    lane = "\n".join(obs_report.fleet_lane(metrics))
    assert "tdtpu_fleet_evacuations_total" in lane
    assert 'tdtpu_comm_timeouts_total{rank="1"}' in lane
    # An evacuated-and-never-rejoined run fails --check...
    rc = obs_report.main([run_dir, "--check", "--require-series", ""])
    assert rc == 1
    # ...unless the operator acknowledges the degraded capacity.
    rc = obs_report.main([run_dir, "--check", "--require-series", "",
                          "--allow-evacuation"])
    assert rc == 0
    # A rejoin answers the evacuation: the debt clears.
    obs.start_run(str(tmp_path / "run2"))
    reg = obs_metrics.registry()
    reg.counter(obs_metrics.FLEET_EVACUATIONS, "evacs").inc()
    reg.counter(obs_metrics.FLEET_REJOINS, "rejoins").inc()
    run_dir2 = obs.finish_run()
    assert obs_report.evacuation_debt(
        obs_report.load_metrics(run_dir2)) == 0
    rc = obs_report.main([run_dir2, "--check", "--require-series", ""])
    assert rc == 0
