"""Host-RAM KV tier + async double-buffered loop (ISSUE 20,
docs/serving.md "KV tiering & the async loop").

The load-bearing contract: a cache-only chain evicted under pool
pressure swaps its pages to bounded host RAM instead of dying; a later
admission whose prompt extends past the device-resident hit RESTORES
the chain through the checksummed stream and must be TOKEN-IDENTICAL
to the cold oracle with zero cold prefill over the restored span.
Integrity failures degrade to cold prefill — never wrong tokens. The
async plan/commit split is a pure reordering: same tokens as the sync
loop, including preempt/resume, with the page auditor clean and the
named ``use-after-swap-out`` hazard flagged when a launch reads a
swapped page.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.analysis.page_audit import PageAuditor
from triton_distributed_tpu.models.config import tiny_config
from triton_distributed_tpu.models.dense import init_dense_llm
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.kv_cache import PageAllocator
from triton_distributed_tpu.obs import goodput as obs_goodput
from triton_distributed_tpu.obs import stepprof as obs_stepprof
from triton_distributed_tpu.runtime import initialize_distributed
from triton_distributed_tpu.serving.kvtier import (
    HostKVTier, HostTierError, HostTierIntegrityError,
)
from triton_distributed_tpu.serving.loop import ServingEngine


@pytest.fixture(scope="module")
def ctx1():
    return initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])


@pytest.fixture(scope="module")
def tiny(ctx1):
    cfg = tiny_config()
    params = init_dense_llm(jax.random.key(0), cfg)
    return cfg, params


def _golden(engine, prompt, gen):
    return np.asarray(
        engine.serve(jnp.asarray([prompt], jnp.int32), gen_len=gen)
    )[0].tolist()


# ---------------------------------------------------------------------------
# HostKVTier — pure-host unit contract (no device, no serving loop).
# ---------------------------------------------------------------------------

def _fetch_const(page):
    """A deterministic fake pool page: bytes derived from the page id,
    so checksum round-trips are meaningful."""
    k = np.full((2, 4), float(page) + 0.5, np.float32)
    v = np.full((2, 4), float(page) - 0.25, np.float32)
    return k, v


def test_disabled_tier_refuses_everything():
    tier = HostKVTier(0, page_size=4, fetch=_fetch_const)
    assert not tier.enabled
    assert tier.swap_out([1, 2, 3, 4], 0) is False
    assert tier.match([1, 2, 3, 4, 5], 0) == []
    assert tier.pages == 0 and tier.swap_outs == 0


def test_swap_out_and_match_walk():
    tier = HostKVTier(1 << 20, page_size=4, fetch=_fetch_const)
    toks = list(range(30, 42))                   # 3 pages of 4
    tier.swap_out(toks[:4], 0)
    tier.swap_out(toks[:8], 1)
    tier.swap_out(toks[:12], 2)
    assert tier.pages == 3 and tier.swap_outs == 3
    # Longer prompt: all three chunks extend it.
    assert len(tier.match(toks + [7, 7], 0)) == 3
    # Identical prompt: the full-prompt chunk is capped out (at least
    # one token must prefill for the next-token logits).
    assert len(tier.match(toks, 0)) == 2
    # From a device-resident hit boundary: the walk starts mid-chain.
    assert tier.match(toks + [7], 4) == [tuple(toks[:8]), tuple(toks[:12])]
    # Unaligned start / diverged tokens find nothing.
    assert tier.match(toks + [7], 2) == []
    assert tier.match(toks[:4] + [99, 99, 99, 99, 1], 4) == []
    # The walk stops at the first missing chunk — no holes.
    assert tier.drop_chain([toks[:8]]) == 1
    assert tier.match(toks + [7, 7], 0) == [tuple(toks[:4])]


def test_swap_out_dedups_by_content():
    tier = HostKVTier(1 << 20, page_size=4, fetch=_fetch_const)
    assert tier.swap_out([1, 2, 3, 4], 0)
    held = tier.bytes_held
    # The same token prefix from a DIFFERENT pool page is the same KV
    # by content addressing: recency refreshes, nothing is re-copied.
    assert tier.swap_out([1, 2, 3, 4], 5)
    assert tier.swap_outs == 1 and tier.bytes_held == held


def test_budget_lru_eviction():
    k, v = _fetch_const(0)
    chunk_bytes = k.nbytes + v.nbytes
    tier = HostKVTier(2 * chunk_bytes, page_size=4, fetch=_fetch_const)
    tier.swap_out([1, 2, 3, 4], 0)
    tier.swap_out([1, 2, 3, 4, 5, 6, 7, 8], 1)
    # Touch the older entry so the SECOND one is the LRU victim.
    tier.chunk([1, 2, 3, 4])
    tier.swap_out([9, 9, 9, 9], 2)
    assert tier.pages == 2 and tier.host_evictions == 1
    assert tier.bytes_held == 2 * chunk_bytes
    assert tuple([1, 2, 3, 4]) in tier._entries      # recently used: kept
    assert tuple([1, 2, 3, 4, 5, 6, 7, 8]) not in tier._entries
    # A chunk that can never fit is refused outright, not thrashed in.
    small = HostKVTier(chunk_bytes - 1, page_size=4, fetch=_fetch_const)
    assert small.enabled
    assert small.swap_out([1, 2, 3, 4], 0) is False
    assert small.pages == 0


def test_chunk_verifies_checksum_and_drops_corrupt():
    tier = HostKVTier(1 << 20, page_size=4, fetch=_fetch_const)
    tier.swap_out([1, 2, 3, 4], 0)
    ent = tier._entries[(1, 2, 3, 4)]
    ent.k = np.array(ent.k)
    ent.k.flat[0] += 64.0                       # rot in host RAM
    with pytest.raises(HostTierIntegrityError, match="checksum mismatch"):
        tier.chunk([1, 2, 3, 4])
    assert tier.integrity_failures == 1
    # The corrupt copy is GONE: a retry prefills cold instead of
    # re-reading the same bytes.
    assert tier.pages == 0
    with pytest.raises(HostTierError, match="evicted between"):
        tier.chunk([1, 2, 3, 4], chunk_idx=0)
    assert HostTierError.transient and HostTierIntegrityError.transient


def test_chaos_hook_drop_and_mutate():
    tier = HostKVTier(1 << 20, page_size=4, fetch=_fetch_const)
    tier.swap_out([1, 2, 3, 4], 0)
    tier.chaos_hook = lambda i, kv: None
    with pytest.raises(HostTierError, match="lost between"):
        tier.chunk([1, 2, 3, 4])
    assert tier.pages == 0                      # dropped, not retryable
    tier.chaos_hook = None
    tier.swap_out([1, 2, 3, 4], 0)
    tier.chaos_hook = lambda i, kv: (kv[0] + 1.0, kv[1])
    with pytest.raises(HostTierIntegrityError):
        tier.chunk([1, 2, 3, 4])


def test_clear_resets_bytes():
    tier = HostKVTier(1 << 20, page_size=4, fetch=_fetch_const)
    tier.swap_out([1, 2, 3, 4], 0)
    tier.swap_out([1, 2, 3, 4, 5, 6, 7, 8], 1)
    assert tier.clear() == 2
    assert tier.pages == 0 and tier.bytes_held == 0
    assert tier.match([1, 2, 3, 4, 5], 0) == []


# ---------------------------------------------------------------------------
# Page auditor — the swap lifecycle and the named hazard.
# ---------------------------------------------------------------------------

def test_note_swap_validates_op():
    al = PageAllocator(4, 4)
    with pytest.raises(ValueError, match="note_swap op"):
        al.note_swap("swapped", 0)


def test_audit_use_after_swap_out():
    aud = PageAuditor(page_size=4)
    aud.record({"op": "alloc", "owner": "prefix:chain", "pages": [0, 1]})
    aud.record({"op": "swap_out", "page": 1})
    aud.note_launch([0, 1], [], site="decode")
    kinds = [v.kind for v in aud.violations]
    assert kinds == ["use-after-swap-out"]
    # Re-allocation scatters fresh bytes: the hazard ends there.
    aud.record({"op": "decref", "page": 1})
    aud.record({"op": "alloc", "owner": "r2", "pages": [1]})
    aud.record({"op": "swap_in", "page": 1})
    n = len(aud.violations)
    aud.note_launch([1], [], site="decode")
    assert len(aud.violations) == n


def test_audit_swap_event_desyncs():
    aud = PageAuditor(page_size=4)
    aud.record({"op": "alloc", "owner": "a", "pages": [0]})
    aud.record({"op": "share", "owner": "b", "pages": [0]})
    aud.record({"op": "swap_out", "page": 0})    # refcount 2: not cache-only
    aud.record({"op": "swap_in", "page": 3})     # free target
    kinds = [v.kind for v in aud.violations]
    assert kinds == ["audit-desync", "audit-desync"]


# ---------------------------------------------------------------------------
# Serving integration — swap-out under pressure, warm restore parity.
# ---------------------------------------------------------------------------

def _build(tiny, ctx1, **kw):
    cfg, params = tiny
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=64,
                    page_size=4)
    kw.setdefault("kv_host_budget_bytes", 1 << 30)
    se = ServingEngine(engine, max_batch=2, num_pages=10,
                       prefill_chunk=4, prefix_cache=True, **kw)
    return engine, se


_PRE = list(range(10, 22))
_WARM = _PRE + [3, 5, 8, 9]
_FAT = list(range(30, 58))


def _pressure_cycle(engine, se):
    """Serve the warm chain, then a fat cold request that forces the
    cache-only chain to swap out. Returns the warm request's golden."""
    g_warm = _golden(engine, _WARM, 5)
    r0, _ = se.submit(_WARM, 5, req_id="t0")
    se.run()
    assert r0.tokens == g_warm
    g_fat = _golden(engine, _FAT, 4)
    rf, _ = se.submit(_FAT, 4, req_id="fat")
    se.run()
    assert rf.tokens == g_fat
    assert se.kvtier.swap_outs > 0, "pool sizing no longer forces swap-out"
    return g_warm


def test_swap_out_then_warm_restore_parity(tiny, ctx1):
    engine, se = _build(tiny, ctx1)
    assert se.kvtier is not None and se.kvtier.enabled
    g_warm = _pressure_cycle(engine, se)
    r2, _ = se.submit(_WARM, 5, req_id="t2")
    se.run()
    assert r2.tokens == g_warm
    assert se.kvtier.restores > 0
    assert r2.restored_tokens_total > 0
    assert r2.prefix_hit_tokens_total >= r2.restored_tokens_total
    # Pool accounting stays exact after the restore landed.
    al = se.sched.allocator
    assert al.free_count + se.prefix.pages_held == al.usable_pages


def test_budget_zero_means_no_tier(tiny, ctx1):
    _, se = _build(tiny, ctx1, kv_host_budget_bytes=0)
    assert se.kvtier is None


def test_corrupt_host_chain_degrades_to_cold_prefill(tiny, ctx1):
    engine, se = _build(tiny, ctx1)
    g_warm = _pressure_cycle(engine, se)
    tier = se.kvtier
    import dataclasses as _dc
    for key, ch in list(tier._entries.items()):
        bad_k = np.array(ch.k)
        bad_k.flat[0] += 1024.0
        tier._entries[key] = _dc.replace(ch, k=bad_k)
    r2, _ = se.submit(_WARM, 5, req_id="t2")
    se.run()
    # Checksum catches the rot, the entry drops, the request recomputes
    # cold — parity held, zero restored tokens, never wrong tokens.
    assert tier.integrity_failures >= 1
    assert r2.tokens == g_warm
    assert r2.restored_tokens_total == 0


def test_restore_drop_mid_stream_recomputes(tiny, ctx1):
    engine, se = _build(tiny, ctx1)
    g_warm = _pressure_cycle(engine, se)
    fired = []

    def drop_once(idx, kv):
        if not fired:
            fired.append(idx)
            return None
        return kv

    se._kvtier_chaos = drop_once
    r2, _ = se.submit(_WARM, 5, req_id="t2")
    se.run()
    assert fired, "chaos hook never fired — no restore was attempted"
    assert se.kvtier.restore_failures >= 1
    assert r2.preemptions >= 1
    assert r2.tokens == g_warm


# ---------------------------------------------------------------------------
# Async double-buffered loop — pure reordering of the sync loop.
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec_k", [0, 2])
def test_async_sync_token_parity(tiny, ctx1, spec_k):
    prompts = [
        (_WARM, 5),
        (_PRE + [3, 5, 8, 30, 31, 32], 6),
        (list(range(30, 50)), 4),
        (_WARM, 5),
    ]
    results = {}
    for mode in ("sync", "async"):
        _, se = _build(tiny, ctx1, spec_k=spec_k,
                       async_loop=(mode == "async"))
        for i, (p, g) in enumerate(prompts):
            se.submit(p, g, req_id=f"r{i}")
        se.run()
        results[mode] = {r.req_id: r.tokens for r in se._finished}
    assert results["sync"] == results["async"]


def test_async_overlaps_and_partitions(tiny, ctx1):
    prof = obs_stepprof.StepProfiler()
    prev_p = obs_stepprof.set_profiler(prof)
    gl = obs_goodput.WorkLedger(interval=2)
    prev_g = obs_goodput.set_ledger(gl)
    try:
        engine, se = _build(tiny, ctx1, async_loop=True)
        g_warm = _pressure_cycle(engine, se)
        r2, _ = se.submit(_WARM, 5, req_id="t2")
        se.run()
    finally:
        obs_stepprof.set_profiler(prev_p)
        obs_goodput.set_ledger(prev_g)
    # Warm restores land at commit boundaries with parity intact.
    assert r2.tokens == g_warm and r2.restored_tokens_total > 0
    recs = prof.records()
    assert any(r.get("overlapped_ms", 0.0) > 0 for r in recs), \
        "no iteration overlapped host work with the in-flight step"
    # The goodput partition invariant holds at commit-time accounting.
    bad = [obs_goodput.check_partition(r) for r in gl.records()]
    assert all(b is None for b in bad), bad


def test_sync_loop_records_no_overlap(tiny, ctx1):
    prof = obs_stepprof.StepProfiler()
    prev_p = obs_stepprof.set_profiler(prof)
    try:
        _, se = _build(tiny, ctx1)
        se.submit(_WARM, 5, req_id="t0")
        se.run()
    finally:
        obs_stepprof.set_profiler(prev_p)
    assert all(r.get("overlapped_ms", 0.0) == 0 for r in prof.records())


def test_report_check_gates_kv_tier_lane(tmp_path):
    """A serving-tier snapshot without the KV host-tier series fails
    --check (swap-out/restore evidence lost); the explicit opt-out or
    the series themselves pass it. The loop publishes the series
    UNCONDITIONALLY (zeros when no tier is configured), so absence
    means "pre-tier run dir", never "tier off"."""
    from triton_distributed_tpu.obs import metrics as obs_metrics
    from triton_distributed_tpu.obs import report as obs_report

    reg = obs_metrics.Registry()
    reg.counter(obs_metrics.SERVE_FINISHED, "x").inc(1)
    reg.gauge(obs_metrics.KV_PAGES_RESIDENT, "x").set(4)
    reg.save(str(tmp_path))
    args = [str(tmp_path), "--check", "--require-series", "",
            "--allow-missing-request-lane", "--allow-missing-step-profile",
            "--allow-missing-goodput"]
    assert obs_report.main(args) == 1
    assert obs_report.main(args + ["--allow-missing-kv-tier"]) == 0
    reg.gauge(obs_metrics.KV_HOST_PAGES, "x").set(0)
    reg.counter(obs_metrics.KV_HOST_RESTORES, "x")
    reg.counter(obs_metrics.KV_HOST_EVICTIONS, "x")
    reg.save(str(tmp_path))
    assert obs_report.main(args) == 0
