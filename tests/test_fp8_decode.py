"""fp8 (e4m3) weight serving on the jit decode ladder — round 6.

The `decode_step_ms_fp8` bench rung serves the shard with e4m3
projection/MLP weights and PURE fp8 dots (models/fp8.fp8_dot — the
configuration that measured 1.81x bf16 at the weight-streaming m=8
decode shape). These tests pin the lane's correctness contract:
token-parity of the fp8 dot path vs the SAME-quantized fp32-emulated
math (e4m3 products are exactly representable in fp32), and the
quantizer's scope (projections only — norms/embed/lm_head keep the
model dtype).
"""

import numpy as np

import jax.numpy as jnp
import jax.random as jrandom

from triton_distributed_tpu.models.config import ModelConfig
from triton_distributed_tpu.models.dense import (
    dense_decode_step, init_dense_llm,
)
from triton_distributed_tpu.models.fp8 import (
    E4M3, fp8_dot, fp8_emulated_dot, quantize_dense_weights,
)
from triton_distributed_tpu.models.kv_cache import init_kv_cache


def _cfg():
    return ModelConfig(hidden_size=256, intermediate_size=256,
                       num_layers=2, num_heads=2, num_kv_heads=1,
                       head_dim=128, vocab_size=512, qk_norm=True)


def test_quantize_scope():
    cfg = _cfg()
    params = init_dense_llm(jrandom.PRNGKey(0), cfg)
    p8 = quantize_dense_weights(params)
    layer = p8["layers"][0]
    for k in ("wq", "wk", "wv", "wo"):
        assert layer["attn"][k].dtype == E4M3
    for k in ("w_gate", "w_up", "w_down"):
        assert layer["mlp"][k].dtype == E4M3
    # Norms, embed and lm_head stay in the model dtype (the fp8 lane
    # covers the weight-streaming projections, like the megakernel's
    # fp8 weight workspace).
    assert p8["embed"].dtype == params["embed"].dtype
    assert layer["attn_norm"].dtype == params["layers"][0][
        "attn_norm"].dtype
    assert layer["attn"]["q_norm"].dtype != E4M3


def test_fp8_dot_matches_emulation():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 64)) * 0.3, jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((64, 32)) * 0.1, jnp.bfloat16)
    got = fp8_dot(x, w)
    ref = fp8_emulated_dot(x, w)
    assert got.dtype == x.dtype
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=1e-2, atol=1e-2)


def test_fp8_decode_token_parity():
    """The fp8 decode chain must produce the SAME tokens as the fp32
    emulation of the identical quantized math — the lane's token-parity
    contract vs the bf16-path-on-quantized-weights golden (VERDICT r5
    #6)."""
    cfg = _cfg()
    params = init_dense_llm(jrandom.PRNGKey(0), cfg)
    p8 = quantize_dense_weights(params)
    cache0 = init_kv_cache(cfg, 1, 128)
    cache0 = cache0._replace(offset=jnp.int32(16))

    def run(dot_fn, steps=6):
        cache, tok = cache0, jnp.zeros((1,), jnp.int32)
        toks = []
        for _ in range(steps):
            logits, cache = dense_decode_step(p8, cfg, tok, cache,
                                              num_ranks=1, mode="ar",
                                              dot_fn=dot_fn)
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
            toks.append(int(tok[0]))
        return toks

    assert run(fp8_dot) == run(fp8_emulated_dot)


def test_fp8_decode_differs_from_bf16_only_by_quantization():
    """Sanity: the fp8 path's logits track the unquantized bf16 path
    within e4m3 quantization error (no wiring bug silently zeroing a
    projection)."""
    cfg = _cfg()
    params = init_dense_llm(jrandom.PRNGKey(0), cfg)
    p8 = quantize_dense_weights(params)
    cache = init_kv_cache(cfg, 1, 128)
    cache = cache._replace(offset=jnp.int32(16))
    tok = jnp.zeros((1,), jnp.int32)
    l8, _ = dense_decode_step(p8, cfg, tok, cache, num_ranks=1,
                              mode="ar", dot_fn=fp8_dot)
    lb, _ = dense_decode_step(params, cfg, tok, cache, num_ranks=1,
                              mode="ar")
    np.testing.assert_allclose(np.asarray(l8, np.float32),
                               np.asarray(lb, np.float32),
                               rtol=0.35, atol=0.35)


def test_fp8_dot_saturates_instead_of_nan():
    """jnp's float->e4m3fn conversion produces NaN (not saturation)
    beyond +-448; one hot activation element must saturate, not NaN the
    whole output row (the silent-argmax-to-token-0 failure)."""
    x = jnp.asarray([[500.0, -1000.0, 2.0, 0.5]], jnp.float32)
    w = jnp.eye(4, dtype=jnp.float32)
    for fn in (fp8_dot, fp8_emulated_dot):
        out = np.asarray(fn(x, w), np.float32)
        assert np.isfinite(out).all(), fn.__name__
        np.testing.assert_allclose(out[0, :2], [448.0, -448.0])


def _moe_cfg():
    return ModelConfig(hidden_size=256, intermediate_size=256,
                       num_layers=1, num_heads=2, num_kv_heads=1,
                       head_dim=128, vocab_size=512, qk_norm=True,
                       num_experts=4, num_experts_per_tok=2,
                       moe_intermediate_size=128)


def test_quantize_covers_moe_experts():
    """ROADMAP 1a tail (round 12): the fp8 exclusion on MoE expert
    weights is LIFTED — the expert stacks (w_gate/w_up/w_down inside
    the 'moe' subtree) quantize to e4m3 and their grouped GEMMs route
    through the dtype-aware ragged_dot (PURE e4m3×e4m3 with fp32
    accumulation — never the losing mixed bf16×fp8 form). The router
    stays full-width: routing decisions keep wide logits and its bytes
    are noise next to the expert stacks."""
    params = init_dense_llm(jrandom.PRNGKey(0), _moe_cfg())
    p8 = quantize_dense_weights(params)
    moe = p8["layers"][0]["moe"]
    for k in ("w_gate", "w_up", "w_down"):
        assert moe[k].dtype == E4M3, k
    assert moe["router"].dtype != E4M3
    # Dense attention projections in the same layer quantize too.
    assert p8["layers"][0]["attn"]["wo"].dtype == E4M3


def test_fp8_moe_forward_matches_emulation():
    """The quantized expert path's parity golden: ragged_dot over e4m3
    experts with a saturate-quantized activation must agree with the
    same quantized math run in fp32 (e4m3 products are exactly
    representable in fp32)."""
    import jax

    from triton_distributed_tpu.models.fp8 import _to_e4m3
    from triton_distributed_tpu.ops.moe import (
        ragged_dot_dtype_aware, sort_by_expert,
    )

    rng = np.random.default_rng(1)
    E, h, f, T = 4, 64, 32, 12
    x = jnp.asarray(rng.standard_normal((T, h)) * 0.4, jnp.float32)
    w = _to_e4m3(jnp.asarray(rng.standard_normal((E, h, f)) * 0.1,
                             jnp.float32))
    ids = jnp.asarray(rng.integers(0, E, T), jnp.int32)
    sidx, gsz = sort_by_expert(ids, E)
    xs = x[sidx]
    got = ragged_dot_dtype_aware(xs, w, gsz)
    ref = jax.lax.ragged_dot(_to_e4m3(xs).astype(jnp.float32),
                             w.astype(jnp.float32), gsz)
    assert got.dtype == x.dtype
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_fp8_moe_decode_runs_end_to_end():
    """A quantized MoE model decodes through dense_decode_step (the
    expert GEMMs hit the dtype-aware path inside moe_tp_fwd_local) with
    finite logits — the wiring proof the scope test alone can't give."""
    cfg = _moe_cfg()
    params = quantize_dense_weights(init_dense_llm(jrandom.PRNGKey(0),
                                                   cfg))
    cache = init_kv_cache(cfg, 1, 16)
    logits, cache = dense_decode_step(
        params, cfg, jnp.zeros((1,), jnp.int32), cache, num_ranks=1,
        mode="ar", dot_fn=fp8_dot)
    out = np.asarray(logits, np.float32)
    assert out.shape == (1, cfg.vocab_size)
    assert np.isfinite(out).all()
