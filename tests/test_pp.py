"""Pipeline-parallel transport + GPipe microbatching (reference
test_pp.py: PP-group splitting + microbatch ping-pong over symmetric
buffers, layers/nvidia/p2p.py CommOp)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.layers.pp import PPStream, pp_pipeline_forward
from triton_distributed_tpu.runtime import shard_map_on


def test_pp_stream_ring(ctx):
    """send_next shifts activations one stage forward around the ring."""
    n, m, cols = 8, 8, 128

    def f(x):
        stream = PPStream(axis="tp", num_ranks=n)
        return stream.send_next(x)

    x = jnp.arange(n * m * cols, dtype=jnp.float32).reshape(n * m, cols)
    y = shard_map_on(ctx, f, in_specs=P("tp"), out_specs=P("tp"))(x)
    expected = np.roll(np.asarray(x).reshape(n, m, cols), 1, axis=0)
    np.testing.assert_allclose(np.asarray(y).reshape(n, m, cols), expected)


def test_pp_pipeline_forward_golden(ctx):
    """n-stage GPipe: each stage adds its stage id; the last stage's output
    must equal x + sum(stage ids) for every microbatch."""
    n, num_mb, mb, cols = 8, 6, 8, 128

    def run(x_mb):
        def stage_fn(x):
            return x + jax.lax.axis_index("tp").astype(x.dtype)

        return pp_pipeline_forward(stage_fn, x_mb, axis="tp", num_ranks=n)

    rng = np.random.default_rng(0)
    x = rng.standard_normal((num_mb, mb, cols)).astype(np.float32)
    # Same microbatches visible on every stage (stage 0 reads them).
    xs = jnp.asarray(np.broadcast_to(x, (n, *x.shape)).reshape(
        n * num_mb, mb, cols))

    out = shard_map_on(ctx, run, in_specs=P("tp"), out_specs=P("tp"))(xs)
    out = np.asarray(out).reshape(n, num_mb, mb, cols)
    # Last stage holds the real outputs.
    expected = x + sum(range(n))
    np.testing.assert_allclose(out[n - 1], expected, rtol=1e-5, atol=1e-5)


def test_p2p_permute_partial_and_multicast(ctx):
    """Arbitrary-pair P2P (ops/p2p.p2p_permute_local): a partial perm with
    a multicast — only some devices send, one src feeds two dsts, idle
    devices zero (ppermute semantics golden)."""
    from triton_distributed_tpu.ops.p2p import p2p_permute_local

    n, m, cols = 8, 8, 128
    perm = [(0, 3), (5, 2), (0, 6)]   # 0 multicasts to 3 and 6; 5 -> 2

    def f(x):
        return p2p_permute_local(x, perm, axis="tp", num_ranks=n)

    x = jnp.arange(n * m * cols, dtype=jnp.float32).reshape(n * m, cols)
    y = shard_map_on(ctx, f, in_specs=P("tp"), out_specs=P("tp"))(x)
    got = np.asarray(y).reshape(n, m, cols)
    blocks = np.asarray(x).reshape(n, m, cols)
    expected = np.zeros_like(blocks)
    for s, d in perm:
        expected[d] = blocks[s]
    np.testing.assert_array_equal(got, expected)


def test_p2p_permute_butterfly_matches_ppermute(ctx):
    """Full non-ring permutation (XOR-1 butterfly) vs jax.lax.ppermute."""
    from triton_distributed_tpu.ops.p2p import p2p_permute_local

    n, m, cols = 8, 16, 128
    perm = [(s, s ^ 1) for s in range(n)]

    def f(x):
        return p2p_permute_local(x, perm, axis="tp", num_ranks=n)

    def golden(x):
        return jax.lax.ppermute(x, "tp", perm)

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((n * m, cols)), jnp.float32)
    y = shard_map_on(ctx, f, in_specs=P("tp"), out_specs=P("tp"))(x)
    g = shard_map_on(ctx, golden, in_specs=P("tp"), out_specs=P("tp"))(x)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(g))


def test_p2p_permute_ring_fast_path(ctx):
    """A perm that IS a uniform ring shift must dispatch the shift kernel
    and stay correct."""
    from triton_distributed_tpu.ops.p2p import p2p_permute_local

    n, m, cols = 8, 8, 128
    perm = [(s, (s + 3) % n) for s in range(n)]

    def f(x):
        return p2p_permute_local(x, perm, axis="tp", num_ranks=n)

    x = jnp.arange(n * m * cols, dtype=jnp.float32).reshape(n * m, cols)
    y = shard_map_on(ctx, f, in_specs=P("tp"), out_specs=P("tp"))(x)
    expected = np.roll(np.asarray(x).reshape(n, m, cols), 3, axis=0)
    np.testing.assert_array_equal(np.asarray(y).reshape(n, m, cols),
                                  expected)


def test_pp_pipeline_interleaved_golden(ctx):
    """Interleaved virtual stages: 2 chunks/device over 8 devices = 16
    virtual stages; chunk c on device d applies (x + 100*c + d). The last
    virtual stage's outputs must match the sequential composition."""
    from triton_distributed_tpu.layers.pp import pp_pipeline_interleaved

    n, chunks, num_mb, mb, cols = 8, 2, 5, 8, 128

    def run(x_mb):
        def stage_fn(c, x):
            return x + (100.0 * c
                        + jax.lax.axis_index("tp").astype(x.dtype))

        return pp_pipeline_interleaved(stage_fn, x_mb, chunks=chunks,
                                       axis="tp", num_ranks=n)

    rng = np.random.default_rng(1)
    x = rng.standard_normal((num_mb, mb, cols)).astype(np.float32)
    xs = jnp.asarray(np.broadcast_to(x, (n, *x.shape)).reshape(
        n * num_mb, mb, cols))

    out = shard_map_on(ctx, run, in_specs=P("tp"), out_specs=P("tp"))(xs)
    out = np.asarray(out).reshape(n, num_mb, mb, cols)
    expected = x + sum(100.0 * c + d for c in range(chunks)
                       for d in range(n))
    np.testing.assert_allclose(out[n - 1], expected, rtol=1e-5, atol=1e-5)


def test_commop_exchange_and_send(ctx):
    """CommOp — the reference PP CommOp layer surface: exchange(perm) and
    single-pair send, composed inside one shard_map region."""
    from triton_distributed_tpu.layers.pp import CommOp

    n, m, cols = 8, 8, 128

    def f(x):
        op = CommOp(axis="tp", num_ranks=n)
        a = op.send(x, src=2, dst=6)          # only device 6 receives
        b = op.exchange(x, [(s, (s + 1) % n) for s in range(n)])  # ring
        return a + b

    x = jnp.arange(n * m * cols, dtype=jnp.float32).reshape(n * m, cols)
    y = shard_map_on(ctx, f, in_specs=P("tp"), out_specs=P("tp"))(x)
    blocks = np.asarray(x).reshape(n, m, cols)
    send_part = np.zeros_like(blocks)
    send_part[6] = blocks[2]
    ring_part = np.roll(blocks, 1, axis=0)
    np.testing.assert_array_equal(np.asarray(y).reshape(n, m, cols),
                                  send_part + ring_part)
