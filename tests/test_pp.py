"""Pipeline-parallel transport + GPipe microbatching (reference
test_pp.py: PP-group splitting + microbatch ping-pong over symmetric
buffers, layers/nvidia/p2p.py CommOp)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.layers.pp import PPStream, pp_pipeline_forward
from triton_distributed_tpu.runtime import shard_map_on


def test_pp_stream_ring(ctx):
    """send_next shifts activations one stage forward around the ring."""
    n, m, cols = 8, 8, 128

    def f(x):
        stream = PPStream(axis="tp", num_ranks=n)
        return stream.send_next(x)

    x = jnp.arange(n * m * cols, dtype=jnp.float32).reshape(n * m, cols)
    y = shard_map_on(ctx, f, in_specs=P("tp"), out_specs=P("tp"))(x)
    expected = np.roll(np.asarray(x).reshape(n, m, cols), 1, axis=0)
    np.testing.assert_allclose(np.asarray(y).reshape(n, m, cols), expected)


def test_pp_pipeline_forward_golden(ctx):
    """n-stage GPipe: each stage adds its stage id; the last stage's output
    must equal x + sum(stage ids) for every microbatch."""
    n, num_mb, mb, cols = 8, 6, 8, 128

    def run(x_mb):
        def stage_fn(x):
            return x + jax.lax.axis_index("tp").astype(x.dtype)

        return pp_pipeline_forward(stage_fn, x_mb, axis="tp", num_ranks=n)

    rng = np.random.default_rng(0)
    x = rng.standard_normal((num_mb, mb, cols)).astype(np.float32)
    # Same microbatches visible on every stage (stage 0 reads them).
    xs = jnp.asarray(np.broadcast_to(x, (n, *x.shape)).reshape(
        n * num_mb, mb, cols))

    out = shard_map_on(ctx, run, in_specs=P("tp"), out_specs=P("tp"))(xs)
    out = np.asarray(out).reshape(n, num_mb, mb, cols)
    # Last stage holds the real outputs.
    expected = x + sum(range(n))
    np.testing.assert_allclose(out[n - 1], expected, rtol=1e-5, atol=1e-5)
