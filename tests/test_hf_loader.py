"""HF checkpoint conversion: our forward must match transformers' Qwen3
logits on the converted weights (the reference loads HF checkpoints
directly — models/utils.py:108; this is the parity proof)."""

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from triton_distributed_tpu.models import (  # noqa: E402
    Engine, config_from_hf, convert_hf_state_dict,
)
from triton_distributed_tpu.models.auto import AutoLLM  # noqa: E402


@pytest.fixture(scope="module")
def hf_model():
    cfg = transformers.Qwen3Config(
        hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=8, num_key_value_heads=8, head_dim=16,
        vocab_size=128, rope_theta=1e6, tie_word_embeddings=False)
    torch.manual_seed(0)
    with torch.device("cpu"):
        m = transformers.Qwen3ForCausalLM(cfg)
    return m.eval()


def test_config_mapping(hf_model):
    cfg = config_from_hf(hf_model.config)
    assert cfg.hidden_size == 64 and cfg.num_layers == 2
    assert cfg.num_kv_heads == 8 and cfg.head_dim == 16
    assert not cfg.is_moe


def test_converted_logits_match_transformers(ctx, hf_model):
    """Full-precision forward parity: prefill logits vs HF on 8-way TP."""
    cfg = config_from_hf(hf_model.config)
    params = convert_hf_state_dict(hf_model.state_dict(), cfg,
                                   dtype=jnp.float32)
    eng = Engine(cfg, params, ctx=ctx, backend="xla", max_seq=32)

    ids = np.array([[3, 17, 42, 99, 7, 56, 11, 88]], np.int32)
    with torch.no_grad():
        ref = hf_model(torch.from_numpy(ids.astype(np.int64))).logits
    ref_last = ref[:, -1].float().numpy()

    logits, _ = eng.prefill(jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(logits), ref_last,
                               rtol=2e-3, atol=2e-3)


def test_auto_llm_from_hf_model(ctx, hf_model):
    eng = AutoLLM.from_hf_model(hf_model, ctx=ctx, dtype=jnp.float32,
                                backend="xla", max_seq=32)
    out = eng.serve(jnp.asarray([[5, 9, 31]], jnp.int32), gen_len=3)
    assert out.shape == (1, 3)


def test_llama_family_logits_match_transformers(ctx):
    """Non-qk-norm families (Llama/Qwen2 style) must convert and match —
    qk_norm is gated on model_type (unit-weight RMSNorm still renormalizes,
    so applying it to Llama heads would corrupt them)."""
    cfg_hf = transformers.LlamaConfig(
        hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=8, num_key_value_heads=8, head_dim=8,
        vocab_size=128, rope_theta=1e4, tie_word_embeddings=False,
        attention_bias=False, mlp_bias=False)
    torch.manual_seed(1)
    with torch.device("cpu"):
        m = transformers.LlamaForCausalLM(cfg_hf)
    m = m.eval()

    cfg = config_from_hf(m.config)
    assert not cfg.qk_norm
    params = convert_hf_state_dict(m.state_dict(), cfg, dtype=jnp.float32)
    eng = Engine(cfg, params, ctx=ctx, backend="xla", max_seq=32)

    ids = np.array([[3, 17, 42, 99, 7]], np.int32)
    with torch.no_grad():
        ref = m(torch.from_numpy(ids.astype(np.int64))).logits[:, -1]
    logits, _ = eng.prefill(jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(logits), ref.float().numpy(),
                               rtol=2e-3, atol=2e-3)


def test_qwen3_moe_logits_match_transformers(ctx):
    """MoE conversion parity: stacked expert weights + router + EP/TP MoE
    forward vs transformers' Qwen3MoeForCausalLM. norm_topk_prob=True is
    the published Qwen3-MoE setting and matches the framework's
    softmax-over-selected router convention."""
    cfg_hf = transformers.Qwen3MoeConfig(
        hidden_size=64, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=8, num_key_value_heads=8, head_dim=16,
        vocab_size=128, rope_theta=1e6, tie_word_embeddings=False,
        num_experts=8, num_experts_per_tok=2, moe_intermediate_size=64,
        norm_topk_prob=True, decoder_sparse_step=1)
    torch.manual_seed(2)
    with torch.device("cpu"):
        m = transformers.Qwen3MoeForCausalLM(cfg_hf)
    m = m.eval()

    cfg = config_from_hf(m.config)
    assert cfg.is_moe and cfg.num_experts == 8
    params = convert_hf_state_dict(m.state_dict(), cfg, dtype=jnp.float32)
    eng = Engine(cfg, params, ctx=ctx, backend="xla", max_seq=32)

    ids = np.array([[3, 17, 42, 99, 7, 56, 11, 88]], np.int32)
    with torch.no_grad():
        ref = m(torch.from_numpy(ids.astype(np.int64))).logits[:, -1]
    logits, _ = eng.prefill(jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(logits), ref.float().numpy(),
                               rtol=5e-3, atol=5e-3)


def test_auto_llm_from_config(ctx):
    from triton_distributed_tpu.models.config import tiny_config

    eng = AutoLLM.from_config(tiny_config(), ctx=ctx, max_seq=16)
    out = eng.serve(jnp.asarray([[1, 2, 3, 4]], jnp.int32), gen_len=2)
    assert out.shape == (1, 2)


def test_norm_topk_prob_false_rejected():
    """Mixtral-style routing (no top-k renormalization) must refuse loudly
    instead of converting with wrong router weights (ADVICE r2)."""
    import pytest

    with pytest.raises(ValueError, match="norm_topk_prob"):
        config_from_hf({
            "model_type": "qwen3_moe", "hidden_size": 64,
            "intermediate_size": 128, "num_hidden_layers": 1,
            "num_attention_heads": 4, "num_key_value_heads": 4,
            "head_dim": 16, "vocab_size": 64, "num_experts": 4,
            "num_experts_per_tok": 2, "moe_intermediate_size": 32,
            "norm_topk_prob": False})
