"""Test harness: a virtual 8-device CPU mesh emulating a TPU slice.

The reference tests require a real 8-GPU node (SURVEY.md §4); here Pallas
TPU-interpret mode (``pltpu.InterpretParams``) faithfully emulates remote DMA
and semaphores across ``xla_force_host_platform_device_count`` CPU devices, so
the whole distributed test suite runs hardware-free.
"""

import os
import sys

# Must run before the CPU client is created.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The sandbox's sitecustomize force-registers a TPU PJRT plugin; override.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from triton_distributed_tpu.runtime import initialize_distributed  # noqa: E402


def _patch_interpret_semaphore_wait() -> None:
    """Replace the interpreter's busy-spin DMA-semaphore wait with a blocking
    condition-variable wait.

    jax 0.9.0's TPU-interpret ``Semaphore.wait(has_tasks=True)`` spins
    (`while True: ... continue`) whenever the count is insufficient and no
    pending task exists — which is the common case in "eager" DMA mode when
    genuinely waiting on another device. With 8 device threads under one GIL,
    the spinners starve the worker and a single collective takes minutes.
    ``signal`` always calls ``cv.notify_all``, so blocking on the cv (with a
    small timeout as a safety net for task-executed increments) is sound.
    Test-harness-only; real-TPU execution is untouched.
    """
    from jax._src.pallas.mosaic.interpret import shared_memory as sm

    def wait(self, value, global_core_id, *, has_tasks=False):
        global_core_id = int(global_core_id)
        assert not self.detect_races, "patched wait does not track vector clocks"
        while True:
            with self.cv:
                if self.count_by_core[global_core_id] >= value:
                    self.count_by_core[global_core_id] -= value
                    return
            task = None
            if has_tasks:
                with self.shared_memory.lock:
                    queue = self.shared_memory.tasks_by_sem[(self.id, global_core_id)]
                    if len(queue) > 0:
                        task = queue.pop()
            if task is not None:
                task()
            else:
                with self.cv:
                    if self.count_by_core[global_core_id] < value:
                        self.cv.wait(timeout=0.005)

    sm.Semaphore.wait = wait


def _patch_io_callback_device_put() -> None:
    """Make io/pure callback impls convert args with numpy directly instead of
    ``device_put`` onto cpu:0.

    On a single-CPU host, ``io_callback_impl`` (jax/_src/callback.py:437)
    device_puts every callback arg onto cpu:0 asynchronously; materializing it
    (``np.array(val)``) then requires the cpu:0 execution queue — which a
    *blocked* pallas-interpret callback (semaphore wait inside a collective
    kernel) may be occupying. Any buffer big enough to take the async
    device_put path deadlocks kernel startup (observed threshold ≈64-128KB).
    The interpret machinery only needs numpy values, so convert in place.
    """
    import numpy as np
    from jax import tree_util
    from jax._src import callback as jcb

    def _sync_io_callback_impl(*args, result_avals, callback, sharding, ordered):
        del result_avals, sharding, ordered
        return tree_util.tree_map(np.asarray, callback(*args))

    jcb.io_callback_impl = _sync_io_callback_impl


if os.environ.get("TDTPU_DETECT_RACES", "0") != "1":
    _patch_interpret_semaphore_wait()
_patch_io_callback_device_put()


@pytest.fixture(scope="session")
def ctx():
    """1-D 8-way tp mesh over the virtual CPU devices."""
    assert len(jax.devices()) == 8, (
        f"expected 8 virtual CPU devices, got {len(jax.devices())} "
        f"({jax.devices()[0].platform}) — XLA_FLAGS applied too late?"
    )
    return initialize_distributed(axis_names=("tp",))
