"""Test harness: a virtual 8-device CPU mesh emulating a TPU slice.

The reference tests require a real 8-GPU node (SURVEY.md §4); here Pallas
TPU-interpret mode (``pltpu.InterpretParams``) faithfully emulates remote DMA
and semaphores across ``xla_force_host_platform_device_count`` CPU devices, so
the whole distributed test suite runs hardware-free.
"""

import os
import sys

# Must run before the CPU client is created.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

# No same-rung serve retries in the suite: a retry re-traces a backend
# that just failed (expensive on the interpret-mode env-failure paths);
# the demotion ladder itself is the recovery under test, and it fires on
# the first failure when the budget is 0 (docs/resilience.md).
os.environ.setdefault("TDTPU_STEP_RETRIES", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The sandbox's sitecustomize force-registers a TPU PJRT plugin; override.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from triton_distributed_tpu.runtime import initialize_distributed  # noqa: E402
from triton_distributed_tpu.runtime.interpret_workarounds import (  # noqa: E402
    apply_interpret_workarounds,
)

apply_interpret_workarounds()


@pytest.fixture(scope="session")
def ctx():
    """1-D 8-way tp mesh over the virtual CPU devices."""
    assert len(jax.devices()) == 8, (
        f"expected 8 virtual CPU devices, got {len(jax.devices())} "
        f"({jax.devices()[0].platform}) — XLA_FLAGS applied too late?"
    )
    return initialize_distributed(axis_names=("tp",))
