"""Tutorial 08 — overlapped GEMM + ReduceScatter (TP row-parallel).

Reference analog: tutorials/08-overlapping-gemm-reduce-scatter.py — the
role-inverted twin of tutorial 07: a persistent GEMM *produces* tiles and
notifies per-tile barriers; the reduce-scatter consumer starts reducing each
chunk as soon as its tiles are ready (gemm_reduce_scatter.py:122-253).

TPU translation (ops/gemm_reduce_scatter.py): one Pallas kernel computes
partial products chunk-by-chunk — each peer's output chunk FIRST — and
pushes each finished chunk to its owner with async remote DMA immediately,
so the wire carries chunk i while the MXU computes chunk i+1. After all
pushes, every rank sums the n contributions that landed in its buffer
(fp32) — reduction work is scattered across ranks, like the reference's
ring-reduce consumer.

Golden: jnp.dot + jax.lax.psum_scatter.
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.ops import gemm_rs  # noqa: E402
from triton_distributed_tpu.runtime import (  # noqa: E402
    initialize_distributed, dist_print, shard_map_on,
)


def main():
    ctx = initialize_distributed(mesh_shape=(8,), axis_names=("tp",))
    n, m, k, ncols = 8, 64, 32, 128   # m divisible by n: per-rank chunks
    rng = np.random.default_rng(0)
    # a: (m, n*k) k-sharded activations; b: (n*k, ncols) row-sharded weight —
    # the standard row-parallel layout (each rank holds a k-slice of both).
    a = jnp.asarray(rng.standard_normal((m, n * k)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((n * k, ncols)) * 0.1, jnp.float32)

    out = gemm_rs(a, b, ctx)

    def golden(a_shard, b_shard):
        partial = jnp.dot(a_shard, b_shard)      # (m, ncols) partial sum
        return jax.lax.psum_scatter(partial, "tp", scatter_dimension=0,
                                    tiled=True)

    ref = shard_map_on(ctx, golden, in_specs=(P(None, "tp"), P("tp", None)),
                       out_specs=P("tp", None))(a, b)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    dist_print(f"tutorial 08 OK — gemm_rs == dot+psum_scatter golden "
               f"({m}x{n * k} @ {n * k}x{ncols})", rank=0)


if __name__ == "__main__":
    main()
