"""Tutorial 09 — the long-context axis: SP AG-attention + distributed
flash-decode.

(Replaces the reference's AMD twins 09/10 with the TPU long-context path.)

Reference analogs:
- prefill: sp_ag_attention_intra_node.py:105-432 — K/V shards are
  all-gathered by copy engines into symmetric buffers while the consumer
  flash-attention kernel waits per-KV-chunk, so attention starts as soon as
  the first chunk lands;
- decode: flash_decode.py:129-1132 — KV cache sequence-sharded across ranks
  ("context parallel"); each rank runs split-KV attention over its shard and
  the partials (acc, LSE) are combined across ranks.

TPU translation:
- sp_ag_attention (ops/sp_ag_attention.py): one Pallas kernel per rank
  pushes its K/V shard to all peers (async remote DMA) and consumes
  KV-chunks in swizzled order, waiting each chunk's semaphore — the
  blockwise-rescaling online-softmax accumulates exactly like flash
  attention, so no second pass;
- flash_decode (ops/flash_decode.py): local split-KV partials, then an
  inter-rank LSE/acc combine (log-sum-exp algebra makes partial attention
  results mergeable: out = sum_i w_i·acc_i with w_i = softmax over LSEs).
  Ragged kv_lens per shard are first-class (a shard can even be empty).

Goldens: dense softmax attention over the gathered sequence.
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from triton_distributed_tpu.ops import flash_decode, sp_ag_attention  # noqa: E402
from triton_distributed_tpu.runtime import (  # noqa: E402
    initialize_distributed, dist_print,
)


def dense_attn(q, k, v, causal):
    """Golden: dense softmax attention with GQA head-group broadcast."""
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    groups = hq // hkv
    kk = np.repeat(k, groups, axis=2)
    vv = np.repeat(v, groups, axis=2)
    logits = np.einsum("bqhd,bkhd->bhqk", q, kk) / np.sqrt(d)
    if causal:
        sk = k.shape[1]
        mask = np.tril(np.ones((sq, sk), bool), k=sk - sq)
        logits = np.where(mask[None, None], logits, -np.inf)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, vv)


def main():
    ctx = initialize_distributed(mesh_shape=(8,), axis_names=("tp",))
    rng = np.random.default_rng(0)

    # --- prefill: sequence-parallel AG attention -------------------------
    b, s, hq, hkv, d = 1, 64, 16, 8, 32   # s is sharded: 8 ranks x 8 rows
    q = rng.standard_normal((b, s, hq, d)).astype(np.float32)
    k = rng.standard_normal((b, s, hkv, d)).astype(np.float32)
    v = rng.standard_normal((b, s, hkv, d)).astype(np.float32)
    out = sp_ag_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          ctx, causal=True)
    np.testing.assert_allclose(np.asarray(out), dense_attn(q, k, v, True),
                               rtol=2e-4, atol=2e-4)
    dist_print("sp_ag_attention OK (causal prefill, seq sharded 8-way)",
               rank=0)

    # --- decode: split-KV across ranks + LSE combine ---------------------
    b, s_shard = 2, 16
    q1 = rng.standard_normal((b, hq, d)).astype(np.float32)
    kc = rng.standard_normal((b, 8 * s_shard, hkv, d)).astype(np.float32)
    vc = rng.standard_normal((b, 8 * s_shard, hkv, d)).astype(np.float32)
    # Ragged cache: each rank's shard holds a different #valid rows.
    kv_lens = np.asarray([16, 7, 12, 0, 16, 1, 9, 4], np.int32)

    out = flash_decode(jnp.asarray(q1), jnp.asarray(kc), jnp.asarray(vc),
                       jnp.asarray(kv_lens), ctx, method="pallas")

    sel = np.concatenate([np.arange(r * s_shard, r * s_shard + kv_lens[r])
                          for r in range(8)])
    ref = dense_attn(q1[:, None], kc[:, sel], vc[:, sel], False)[:, 0]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)
    dist_print("flash_decode OK (ragged split-KV + inter-rank LSE combine)",
               rank=0)
    dist_print("tutorial 09 OK", rank=0)


if __name__ == "__main__":
    main()
