"""Tutorial 10 — the MegaKernel: a whole model step as ONE persistent kernel.

Reference analog: mega_triton_kernel/ (SURVEY.md §2.7) — the reference's
best decode latencies (3.33ms Qwen3-8B vs 4.65ms kernel-by-kernel,
BASELINE.md) come from compiling the entire decode step into a single
persistent "MegaTritonKernel": every SM loops over a static work queue,
waits its tasks' dependencies on a device scoreboard, and dispatches tile
kernels by task type.

TPU translation (megakernel/): the same task-graph machinery, re-shaped for
TPU cores:

- ModelBuilder analog (``MegaKernelBuilder``): record tensors + tasks
  (gemm / add / silu_mul / rms_norm / all_reduce / ...) building a
  dependency DAG — the reference's ``ModelBuilder.make_*`` surface;
- scheduler: dependency-respecting task order, computed by the *native C++
  scheduler* (megakernel/native/scheduler.cc, ctypes-loaded, Kahn fallback
  in Python) — the reference's static SM-queue scheduler analog;
- kernel: ONE ``pallas_call`` whose grid walks the task queue; tasks read/
  write tiles of a shared HBM workspace, staged through VMEM per task. The
  AllReduce task does remote DMA + semaphores *inside* the megakernel, so
  even cross-device communication never leaves the single launch.

Below: a 2-layer SwiGLU MLP decode block with a TP AllReduce after each
down-projection, run as one kernel across the 8-device mesh.
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.megakernel import MegaKernelBuilder  # noqa: E402
from triton_distributed_tpu.runtime import (  # noqa: E402
    initialize_distributed, dist_print, shard_map_on,
)


def main():
    ctx = initialize_distributed(mesh_shape=(8,), axis_names=("tp",))
    n, m, h, f = 8, 128, 256, 128   # f = per-rank FFN shard (row-parallel)

    mb = MegaKernelBuilder()
    x = mb.tensor(m, h)
    w_gate = mb.tensor(h, f)
    w_up = mb.tensor(h, f)
    w_down = mb.tensor(f, h)
    gate = mb.tensor(m, f)
    up = mb.tensor(m, f)
    act = mb.tensor(m, f)
    y = mb.tensor(m, h)

    # One TP MLP block: col-parallel gate/up (each rank holds an f-shard),
    # row-parallel down, AllReduce of the partial outputs — all tasks in one
    # queue; the scheduler orders them by the dependency DAG.
    mb.gemm(gate, x, w_gate)
    mb.gemm(up, x, w_up)
    mb.silu_mul(act, gate, up)
    mb.gemm(y, act, w_down)
    mb.all_reduce(y)

    prog = mb.compile(num_ranks=n, axis="tp")
    dist_print(f"megakernel compiled: {prog.queue.shape[0]} tasks in one launch",
               rank=0)

    rng = np.random.default_rng(0)
    ax = rng.standard_normal((m, h)).astype(np.float32) * 0.2
    awg = rng.standard_normal((n, h, f)).astype(np.float32) * 0.1
    awu = rng.standard_normal((n, h, f)).astype(np.float32) * 0.1
    awd = rng.standard_normal((n, f, h)).astype(np.float32) * 0.1

    fn = shard_map_on(
        ctx,
        lambda wg, wu, wd: prog.run(
            {x: jnp.asarray(ax), w_gate: wg[0], w_up: wu[0], w_down: wd[0]},
            outputs=[y])[0][None],
        (P("tp"), P("tp"), P("tp")), P("tp"))
    got = np.asarray(fn(jnp.asarray(awg), jnp.asarray(awu), jnp.asarray(awd)))

    # Golden: the same TP MLP in numpy (sum over rank shards at the end).
    ref = 0.0
    for d in range(n):
        g = ax @ awg[d]
        ref = ref + (g / (1 + np.exp(-g)) * (ax @ awu[d])) @ awd[d]
    for d in range(n):
        np.testing.assert_allclose(got[d], ref, rtol=2e-3, atol=2e-3)

    dist_print("tutorial 10 OK — TP MLP + AllReduce as one persistent "
               "megakernel", rank=0)


if __name__ == "__main__":
    main()
