"""Tutorial 01 — notify/wait: the signal primitives everything is built on.

Reference analog: tutorials/01-distributed-notify-wait.py (a producer rank
sets a symmetric flag with ``dl.notify``; a consumer spins in ``dl.wait``).

TPU translation: the "symmetric flag" is a Pallas *semaphore*. ``dl.notify``
signals a peer's semaphore across ICI (the NVSHMEM ``signal_op`` analog);
``dl.wait`` blocks until the local semaphore reaches a value (the
``signal_wait_until`` / spin-wait-PTX analog). Two deltas from the CUDA
semantics, documented in language/distributed_ops.py:

- waits are *consuming* by default (semaphore decrements), so signal values
  don't accumulate across kernel calls;
- the data→flag ordering the reference gets from release/acquire PTX comes
  for free: remote DMA completion signals the receiver's semaphore.

Here every rank pushes a row to its right neighbor, notifies it, and only
reads its own buffer after waiting — a 1-hop producer/consumer handshake.
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu import language as dl  # noqa: E402
from triton_distributed_tpu.language import shmem_device as shmem  # noqa: E402
from triton_distributed_tpu.language.core import kernel_call, any_spec  # noqa: E402
from triton_distributed_tpu.runtime import (  # noqa: E402
    initialize_distributed, shard_map_on, dist_print,
)


def kernel(in_ref, out_ref, send_sem, recv_sem, flag, scratch):
    me = dl.rank("tp")
    n = dl.num_ranks("tp")
    right = jax.lax.rem(me + 1, n)

    # PRODUCER half: push my block into my right neighbor's out_ref. The
    # DMA's recv semaphore fires on the *destination* device when the bytes
    # have landed (putmem_nbi_block, shmem_device.py).
    rdma = shmem.putmem_nbi_block(in_ref, out_ref, send_sem, recv_sem, right)

    # Tell the neighbor the payload is complete: notify = remote semaphore
    # signal (reference dl.notify -> nvshmemx_signal_op).
    dl.notify(flag, right, inc=1)

    # CONSUMER half: wait for my left neighbor's notify, then use the data.
    # wait() consumes the signal; the rdma recv wait orders the data itself.
    dl.wait(flag, 1)
    rdma.wait()   # waits send-side completion too (nbi -> quiet analog)
    # out_ref lives in HBM (DMA-addressable); compute must stage via VMEM.
    pltpu.sync_copy(out_ref, scratch)
    scratch[...] = scratch[...] * 2.0  # safe: producer finished
    pltpu.sync_copy(scratch, out_ref)


def main():
    ctx = initialize_distributed(mesh_shape=(8,), axis_names=("tp",))

    def f(x):
        return kernel_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[any_spec()],
            out_specs=any_spec(),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA(()),      # send completion
                pltpu.SemaphoreType.DMA(()),      # recv completion
                pltpu.SemaphoreType.REGULAR,      # the notify flag
                pltpu.VMEM((1, 128), jnp.float32),
            ],
        )(x)

    x = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128)
    y = shard_map_on(ctx, f, in_specs=P("tp"), out_specs=P("tp"))(x)

    expected = np.roll(np.asarray(x).reshape(8, 1, 128), 1, axis=0)
    expected = expected.reshape(8, 128) * 2.0
    np.testing.assert_allclose(np.asarray(y), expected)
    dist_print("tutorial 01 OK — notify/wait handshake verified", rank=0)


if __name__ == "__main__":
    main()
