"""Tutorial 03 — inter-slice (two-level) AllGather over ICI + DCN.

Reference analog: tutorials/03-inter-node-allgather.py — a 2D schedule that
pairs intra-node copy-engine transfers with inter-node NVSHMEM puts
(kernels/nvidia/allgather.py:293-378).

TPU translation: the two tiers are the ICI torus (intra-slice, Pallas remote
DMA — our tier-1 kernels) and the data-center network (inter-slice DCN),
which Pallas cannot DMA across. The idiomatic split (SURVEY.md §7):

    tier 1 (ici / "tp" axis):  Pallas push/ring kernels      <- tutorial 02
    tier 2 (dcn axis):         XLA collectives over DCN

ops/two_level.py composes them: gather intra-slice first (fast links,
bulk of the fan-in), then all_gather the slice-local results across the
"dcn" axis with jax.lax — exactly how the reference nests CE-intranode
inside NVSHMEM-internode rings.

Run on a (dcn=2, tp=4) mesh: 2 emulated slices of 4 devices.
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from triton_distributed_tpu.ops.two_level import all_gather_2d  # noqa: E402
from triton_distributed_tpu.runtime import (  # noqa: E402
    initialize_distributed, dist_print,
)


def main():
    ctx = initialize_distributed(mesh_shape=(2, 4), axis_names=("dcn", "tp"))
    N, m, cols = 8, 16, 256   # 8 global devices, row-shard per device
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((N * m, cols)), jnp.float32)

    out = all_gather_2d(x, ctx)   # ICI pallas gather, then DCN XLA gather
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=0, atol=0)
    dist_print("tutorial 03 OK — two-level AG (ICI pallas + DCN XLA)", rank=0)


if __name__ == "__main__":
    main()
