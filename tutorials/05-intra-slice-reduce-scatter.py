"""Tutorial 05 — intra-slice ReduceScatter (ring) + AllReduce on top.

Reference analog: tutorials/05-intra-node-reduce-scatter.py (scatter + ring
reduce over per-node symmetric buffers, kernels/nvidia/reduce_scatter.py).

TPU translation (ops/reduce_scatter.py, ops/allreduce.py): the ring
reduce-scatter sends each chunk around the ICI ring, adding the local
contribution at every hop — after n-1 hops, rank d holds the fully reduced
chunk d. fp32 accumulation regardless of input dtype (the reference's
Triton kernels accumulate in fp32 the same way).

AllReduce = ReduceScatter + AllGather ("two-shot"), or a one-shot push for
small payloads where a single fan-in round beats two phases; AUTO selects by
size via the perf model — the analog of the reference's
get_auto_allreduce_method (allreduce.py:1101).
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from triton_distributed_tpu.ops import (  # noqa: E402
    AllReduceMethod, all_reduce, reduce_scatter,
)
from triton_distributed_tpu.runtime import (  # noqa: E402
    initialize_distributed, dist_print,
)


def main():
    ctx = initialize_distributed(mesh_shape=(8,), axis_names=("tp",))
    n, m, cols = 8, 16, 256
    rng = np.random.default_rng(0)

    # Every device holds a full (n*m, cols) tensor of contributions.
    x = jnp.asarray(rng.standard_normal((n, n * m, cols)), jnp.float32)
    out = reduce_scatter(x, ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0),
                               rtol=1e-4, atol=1e-4)
    dist_print("reduce_scatter ring OK", rank=0)

    for method in (AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT,
                   AllReduceMethod.AUTO):
        y = jnp.asarray(rng.standard_normal((n, m, cols)), jnp.float32)
        out = all_reduce(y, ctx, method=method)
        np.testing.assert_allclose(np.asarray(out), np.asarray(y).sum(0),
                                   rtol=1e-4, atol=1e-4)
        dist_print(f"all_reduce[{method.name}] OK", rank=0)

    dist_print("tutorial 05 OK", rank=0)


if __name__ == "__main__":
    main()
