"""Tutorial 07 — overlapped AllGather + GEMM (the flagship TP pattern).

Reference analog: tutorials/07-overlapping-allgather-gemm.py — copy engines
all-gather activation shards while a persistent GEMM consumes each shard the
moment its per-rank barrier fires, visiting tiles in rank-swizzled order so
compute starts on locally available data (allgather_gemm.py:158-264).

TPU translation (ops/allgather_gemm.py): ONE Pallas kernel plays both roles —

- producer: fires async remote DMA pushes of the local shard to every peer
  *before* any compute, each carrying a per-source-rank semaphore;
- consumer: walks M-tiles in swizzled order (own shard first), waiting each
  source rank's semaphore only when it first touches that rank's rows, and
  runs the pipelined MXU matmul (ops/tiling.py matmul_tiles) per chunk.

The DMA engines and the MXU are independent hardware: pushes fly while the
first (local) chunk is already computing — the same overlap the reference
builds from CUDA streams, with zero streams.

Golden: jax.lax.all_gather + jnp.dot (the reference checks against
torch.distributed.all_gather_into_tensor + torch.matmul).
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.ops import ag_gemm  # noqa: E402
from triton_distributed_tpu.runtime import (  # noqa: E402
    initialize_distributed, dist_print, shard_map_on,
)


def main():
    ctx = initialize_distributed(mesh_shape=(8,), axis_names=("tp",))
    n, m, k, ncols = 8, 32, 256, 64   # per-rank shard sizes
    rng = np.random.default_rng(0)
    # a: (n*m, k) row-sharded activations; b: (k, n*ncols) column-sharded
    # TP weight — the standard column-parallel layout.
    a = jnp.asarray(rng.standard_normal((n * m, k)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((k, n * ncols)) * 0.1, jnp.float32)

    out = ag_gemm(a, b, ctx)

    # Golden path: plain XLA collective + dot under the same sharding.
    def golden(a_shard, b_shard):
        a_full = jax.lax.all_gather(a_shard, "tp", axis=0, tiled=True)
        return jnp.dot(a_full, b_shard)

    ref = shard_map_on(ctx, golden, in_specs=(P("tp"), P(None, "tp")),
                       out_specs=P(None, "tp"))(a, b)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    dist_print(f"tutorial 07 OK — ag_gemm == AG+dot golden "
               f"({n * m}x{k} @ {k}x{n * ncols})", rank=0)


if __name__ == "__main__":
    main()
