"""Tutorial 12 — barrier-free steady-state collectives (the decode loop).

Reference analog: the ``call_count`` parity protocol of
``low_latency_all_to_all.py:125-175`` — double-buffered symmetric workspaces
flipped per call so repeated decode-step collectives never pay a full-mesh
barrier. Round-2 VERDICT flagged that every collective here opened with
``barrier_all`` (two extra sync phases per transformer layer on the decode
path); the ``*_stream`` variants close that.

The protocol, in one paragraph: each op owns ONE persistent workspace with
TWO parity slabs; call t uses slab t%2 and a per-parity recv semaphore. A
rank can only reach call t+2 (reusing slab p) after completing call t+1,
which required a delivery from EVERY peer, which each peer sent only after
finishing its call-t reads of slab p — the DMA-completion chain itself
orders slab reuse, no barrier needed. Persistence matters: the workspace is
caller-owned and threaded through the loop (donated/aliased), because a
per-call transient buffer could be remotely written before the peer's
allocation even exists — which is exactly what the barrier variant's entry
barrier protects against.

Three streams share the pattern (ops/allreduce.py, ops/allgather.py,
ops/all_to_all.py); the Engine threads the AR stream through every
mode="ar" reduction of the dense decode step automatically.
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from triton_distributed_tpu.ops.allgather import (  # noqa: E402
    ag_stream_workspace, all_gather_stream,
)
from triton_distributed_tpu.ops.allreduce import (  # noqa: E402
    all_reduce_stream, ar_stream_workspace,
)
from triton_distributed_tpu.runtime import (  # noqa: E402
    dist_print, initialize_distributed, shard_map_on,
)


def main():
    ctx = initialize_distributed(mesh_shape=(8,), axis_names=("tp",))
    n, m, cols, steps = 8, 16, 128, 50
    rng = np.random.default_rng(0)
    base = rng.standard_normal((n, m, cols)).astype(np.float32)

    def decode_loop(xl):
        """A mock decode loop: one AR + one AG per 'layer step', every call
        riding the parity workspaces — zero barriers in steady state."""
        xl = xl[0]
        ar_ws, ar_idx = ar_stream_workspace(n, m, cols, xl.dtype)
        ag_ws, ag_idx = ag_stream_workspace(n, m, cols, xl.dtype)
        want_sum = jax.lax.psum(xl, "tp")
        want_cat = jax.lax.all_gather(xl, "tp", tiled=True)

        def body(t, carry):
            ar_ws, ar_idx, ag_ws, ag_idx, err = carry
            x_t = xl * (1.0 + t)
            # A rotating straggler widens every reuse window — the protocol
            # must stay exact regardless of which rank lags.
            s, ar_ws, ar_idx = all_reduce_stream(
                x_t, ar_ws, ar_idx, axis="tp", num_ranks=n,
                straggler=("rotate", 512))
            g, ag_ws, ag_idx = all_gather_stream(
                x_t, ag_ws, ag_idx, axis="tp", num_ranks=n)
            err = jnp.maximum(err, jnp.max(jnp.abs(s / (1.0 + t) - want_sum)))
            err = jnp.maximum(err, jnp.max(jnp.abs(g / (1.0 + t) - want_cat)))
            return ar_ws, ar_idx, ag_ws, ag_idx, err

        init = (ar_ws, ar_idx, ag_ws, ag_idx, jnp.float32(0))
        *_, err = jax.lax.fori_loop(0, steps, body, init)
        return err[None]

    fn = shard_map_on(ctx, decode_loop, P("tp"), P("tp"))
    err = float(np.max(np.asarray(fn(jnp.asarray(base)))))
    assert err < 1e-3, err
    dist_print(f"{steps} barrier-free AR+AG steps, max err {err:.2e}", rank=0)
    dist_print("tutorial 12 OK", rank=0)


if __name__ == "__main__":
    main()
