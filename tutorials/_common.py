"""Shared tutorial bootstrap.

Every tutorial runs in one of two environments:

- a real TPU slice: run as-is (`python tutorials/0X-....py`) — the mesh spans
  the actual devices and Pallas kernels compile through Mosaic;
- no TPU / a single chip: an 8-device *virtual CPU mesh* is created and the
  kernels run in Pallas TPU-interpret mode, which faithfully emulates remote
  DMA + semaphores (the reference's tutorials, by contrast, need a real
  8-GPU node — SURVEY.md §4).

Call ``bootstrap()`` before importing jax-dependent tutorial code.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEVICES = 8

# XLA parses XLA_FLAGS once, at first backend initialization — even probing
# the TPU backend consumes them. Set the virtual-CPU device count at module
# import, before any jax touch (it does not affect the TPU platform).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={N_DEVICES}")


def bootstrap(n_devices: int = N_DEVICES):
    """Return a jax module guaranteed to see >= n_devices devices.

    Once a platform initializes it cannot be switched in-process, so the
    choice is made up front: ``TDTPU_TUTORIALS_ON_TPU=1`` runs on the real
    TPU slice (set it on a pod slice with >= n_devices chips); the default
    is the 8-device virtual CPU mesh, where Pallas interpret mode emulates
    remote DMA + semaphores faithfully.
    """
    import jax

    if os.environ.get("TDTPU_TUTORIALS_ON_TPU", "") == "1":
        assert len(jax.devices()) >= n_devices, (
            f"TDTPU_TUTORIALS_ON_TPU=1 but only {len(jax.devices())} devices")
        return jax
    jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) >= n_devices, (
        f"{len(jax.devices())} devices after forcing CPU — another jax API "
        "call initialized the backend before bootstrap()")
    return jax
