"""Tutorial 04 — low-latency MoE AllToAll (EP dispatch/combine).

Reference analog: tutorials/04-deepseek-infer-all2all.py — the DeepSeek-style
inference AllToAll that posted 137µs vs DeepEP's 182µs (BASELINE.md):
one CUDA block per peer, `putmem_nbi_block` for payload+splits, a signal per
peer, double-buffered by call parity (low_latency_all_to_all.py:36-279).

TPU translation (ops/all_to_all.py): the same static-shape design transfers
directly — it is *already* what XLA wants:

- every (src, dst) slot is padded to a fixed ``cap`` rows ("MAX_M padding"),
  so shapes are static under jit;
- the kernel pushes payload + split counts to each peer with remote DMA and
  signals that peer's semaphore; the consumer side waits one signal per
  peer — no global barrier;
- ``dispatch_layout`` / ``combine_layout`` are the pure-JAX (argsort /
  segment-sum) analogs of the reference's csrc alignment op
  (moe_utils.cu:61) building send buffers from router decisions.

The golden check: recv[d, p] must equal send[p, d] — an AllToAll is a
transpose of the (src, dst) slot matrix.
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from triton_distributed_tpu.ops import (  # noqa: E402
    combine_layout, dispatch_layout, fast_all_to_all,
)
from triton_distributed_tpu.runtime import (  # noqa: E402
    initialize_distributed, dist_print,
)


def main():
    ctx = initialize_distributed(mesh_shape=(8,), axis_names=("tp",))
    n, experts_per_rank, cap, hidden, m = 8, 4, 64, 128, 48
    num_experts = n * experts_per_rank
    rng = np.random.default_rng(0)

    # Router output: m tokens per device, each assigned one expert (topk is
    # handled a layer up — layers/ep_moe.py feeds one (token, expert) pair
    # per selected expert).
    tokens = rng.standard_normal((n, m, hidden)).astype(np.float32)
    expert_ids = rng.integers(0, num_experts, size=(n, m)).astype(np.int32)

    # 1. Build the padded per-peer send layout (pure JAX, per device).
    layout = jax.vmap(
        lambda t, e: dispatch_layout(t, e, num_experts, n, cap))(
            jnp.asarray(tokens), jnp.asarray(expert_ids))

    # 2. The AllToAll itself: remote DMA push + per-peer signals.
    recv, recv_splits = fast_all_to_all(layout.send_buf, layout.send_splits,
                                        ctx)

    # Golden: the slot matrix transposes.
    np.testing.assert_array_equal(
        np.asarray(recv_splits),
        np.swapaxes(np.asarray(layout.send_splits), 0, 1))
    r, s = np.asarray(recv), np.asarray(layout.send_buf)
    for d in range(n):
        for p in range(n):
            rows = int(np.asarray(recv_splits)[d, p].sum())
            np.testing.assert_allclose(r[d, p, :rows], s[p, d, :rows])
    dist_print("dispatch OK (recv == send^T)", rank=0)

    # 3. Post-process for the expert MLP: group received tokens per local
    # expert (reference all_to_all_post_process). Every token routed to
    # global expert d*epr+j anywhere in the mesh must land on device d,
    # local group j.
    flat, local_eids, group_sizes = jax.vmap(combine_layout)(recv, recv_splits)
    flat, local_eids = np.asarray(flat), np.asarray(local_eids)
    for d in range(n):
        for j in range(experts_per_rank):
            want = tokens[expert_ids == d * experts_per_rank + j]
            got = flat[d][local_eids[d] == j]
            assert got.shape == want.shape
            np.testing.assert_allclose(
                got[np.lexsort(got.T)], want[np.lexsort(want.T)])
    dist_print("combine_layout OK (tokens grouped per local expert)", rank=0)
    dist_print("tutorial 04 OK", rank=0)


if __name__ == "__main__":
    main()
