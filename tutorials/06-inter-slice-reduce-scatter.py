"""Tutorial 06 — inter-slice (two-level) ReduceScatter + AllReduce.

Reference analog: tutorials/06-inter-node-reduce-scatter.py — intra-node
scatter/ring-reduce nested inside inter-node p2p transfers
(kernels/nvidia/reduce_scatter.py:506, 2D context at :47-147).

TPU translation (ops/two_level.py): reduce intra-slice first over ICI with
the Pallas ring (bulk of the reduction on the fast links), then finish
across slices with an XLA psum_scatter/psum over DCN. The composition
mirrors the reference's two-tier design; only the inter tier's transport
differs (XLA DCN collectives instead of NVSHMEM RDMA).
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from triton_distributed_tpu.ops.two_level import (  # noqa: E402
    all_reduce_2d, reduce_scatter_2d,
)
from triton_distributed_tpu.runtime import (  # noqa: E402
    initialize_distributed, dist_print,
)


def main():
    ctx = initialize_distributed(mesh_shape=(2, 4), axis_names=("dcn", "tp"))
    N, m, cols = 8, 16, 256
    rng = np.random.default_rng(0)

    x = jnp.asarray(rng.standard_normal((N, N * m, cols)), jnp.float32)
    out = reduce_scatter_2d(x, ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0),
                               rtol=1e-4, atol=1e-4)
    dist_print("reduce_scatter_2d OK", rank=0)

    y = jnp.asarray(rng.standard_normal((N, m, cols)), jnp.float32)
    out = all_reduce_2d(y, ctx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(y).sum(0),
                               rtol=1e-4, atol=1e-4)
    dist_print("tutorial 06 OK — two-level RS/AR (ICI pallas + DCN XLA)",
               rank=0)


if __name__ == "__main__":
    main()
