"""Tutorial 02 — intra-slice AllGather: push, ring, and auto-select.

Reference analog: tutorials/02-intra-node-allgather.py (7 AllGather methods
over NVLink copy engines + NVSHMEM; kernels/nvidia/allgather.py:81-539).

TPU translation: there are no copy-engine streams and no switch multicast —
there is an ICI torus where every hop is a remote DMA. The method space
collapses to the two schedules that matter (ops/allgather.py):

- FULL_MESH_PUSH: every rank pushes its shard to all peers at once; all
  sends fly in parallel, finishing in one "round" of per-link time. Best
  for the small/medium sizes where latency dominates.
- RING: n-1 neighbor hops, each forwarding the chunk just received. Total
  bytes per link are the same, but hops serialize — what the ring buys is
  per-hop buffering (only neighbor traffic) for very large payloads.
- AUTO picks by message size with the analytic model in
  runtime/perf_model.py (the reference picks by NVLink topology probing,
  allgather.py:57-72).

Every method is validated against the XLA collective (jax.lax.all_gather) —
the same golden the reference takes from torch.distributed.
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from triton_distributed_tpu.ops import AllGatherMethod, all_gather  # noqa: E402
from triton_distributed_tpu.runtime import (  # noqa: E402
    initialize_distributed, dist_print,
)


def main():
    ctx = initialize_distributed(mesh_shape=(8,), axis_names=("tp",))
    n, m, cols = 8, 32, 256
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((n * m, cols)), jnp.float32)

    golden = np.asarray(x)  # all_gather of row-shards == the full array

    for method in (AllGatherMethod.FULL_MESH_PUSH, AllGatherMethod.RING_1D,
                   AllGatherMethod.AUTO):
        out = all_gather(x, ctx, method=method)
        np.testing.assert_allclose(np.asarray(out), golden, rtol=0, atol=0)
        dist_print(f"all_gather[{method.name}] OK ({n * m}x{cols})", rank=0)

    dist_print("tutorial 02 OK", rank=0)


if __name__ == "__main__":
    main()
