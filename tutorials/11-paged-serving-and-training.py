"""Tutorial 11 — beyond the reference: paged serving + TP training.

The reference is inference-only with a linear KV cache. Two capabilities
this framework adds on top of its inventory:

1. **Paged-KV serving** (`Engine(page_size=...)`): the KV cache lives in
   fixed-size pages with per-sequence page tables — sequences at
   DIFFERENT lengths decode in one step (continuous batching) and can
   share pages (prefix caching). The attention kernel walks the page
   table from SMEM scalar prefetch and DMAs exactly the valid pages
   (ops/paged_attention.py).

2. **TP training** (`models/train.py`): the SAME sharded param pytree
   that serves inference also trains — `jax.jit` over NamedSharding
   params lets XLA place the TP collectives (GSPMD), with the AdamW
   state donated step to step.
"""

from _common import bootstrap

jax = bootstrap()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from triton_distributed_tpu.models.config import tiny_config  # noqa: E402
from triton_distributed_tpu.models.dense import init_dense_llm  # noqa: E402
from triton_distributed_tpu.models.engine import Engine  # noqa: E402
from triton_distributed_tpu.models.train import make_train_step  # noqa: E402
from triton_distributed_tpu.runtime import (  # noqa: E402
    initialize_distributed, dist_print,
)


def main():
    ctx = initialize_distributed(mesh_shape=(8,), axis_names=("tp",))
    cfg = tiny_config()
    rng = np.random.default_rng(0)

    # --- 1. paged vs linear serving: identical tokens ---------------------
    params = init_dense_llm(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    linear = Engine(cfg, params, ctx=ctx, backend="xla", max_seq=32)
    paged = Engine(cfg, params, ctx=ctx, backend="xla", max_seq=32,
                   page_size=8)
    t_lin = np.asarray(linear.serve(ids, gen_len=5))
    t_paged = np.asarray(paged.serve(ids, gen_len=5))
    np.testing.assert_array_equal(t_lin, t_paged)
    dist_print(f"paged == linear serving OK (tokens {t_paged[0].tolist()})",
               rank=0)

    # --- 2. TP training: loss decreases on the sharded params -------------
    init_state, train_step = make_train_step(cfg, ctx, learning_rate=3e-3)
    state = init_state(params)
    batch = rng.integers(0, cfg.vocab_size, (2, 13)).astype(np.int32)
    x, y = jnp.asarray(batch[:, :-1]), jnp.asarray(batch[:, 1:])
    losses = []
    for _ in range(6):
        state, loss = train_step(state, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    dist_print(f"training OK (loss {losses[0]:.3f} -> {losses[-1]:.3f}, "
               "params TP-sharded via GSPMD)", rank=0)
    dist_print("tutorial 11 OK", rank=0)


if __name__ == "__main__":
    main()
